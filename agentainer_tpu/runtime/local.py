"""Local process backend — real engine subprocesses on the TPU-VM.

This is the production stand-in for the reference's Docker daemon: each agent
engine runs as an OS process serving HTTP on a localhost port (the analogue
of a container serving :8000 on the bridge network, reference agent.go:431-508
+ server.go:546), with:

- graceful stop: SIGTERM then SIGKILL after the reference's 10s deadline
  (agent.go:183-215);
- pause/resume via SIGSTOP/SIGCONT (docker pause/unpause);
- restart policy: when the agent was deployed with auto-restart, a watcher
  respawns the engine on unexpected exit (RestartPolicy "always" iff
  AutoRestart, agent.go:482-495);
- engine events pushed to the reconciler when the watcher observes a state
  change (Docker event stream analogue, state_sync.go:253-309);
- stdout/stderr captured to per-engine log files for ``GetLogs`` parity
  (agent.go:411-429).

TPU chip binding: engines receive their chip assignment via env and carve
the slice with ``TPU_VISIBLE_DEVICES``/``JAX_PLATFORMS`` so two engines never
fight over the same chips.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from ..core.spec import Agent
from ..store.base import Store
from .backend import Backend, EngineInfo, EngineState


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class _EngineRec:
    engine_id: str
    agent_id: str
    port: int
    cmd: list[str]
    env: dict[str, str]
    chips: tuple[int, ...]
    auto_restart: bool
    log_path: Path
    proc: subprocess.Popen | None = None
    paused: bool = False
    desired_running: bool = False
    restarts: int = 0
    log_file: object = None


class LocalBackend(Backend):
    def __init__(
        self,
        store: Store | None = None,
        data_dir: str | Path | None = None,
        python: str = sys.executable,
        ready_timeout_s: float = 60.0,
    ):
        self.store = store
        self.python = python
        self.ready_timeout_s = ready_timeout_s
        self.control_url = ""
        self.store_sock = ""
        self.internal_token = ""
        self._dir = Path(data_dir or tempfile.mkdtemp(prefix="atpu-engines-")).expanduser()
        (self._dir / "engines").mkdir(parents=True, exist_ok=True)
        self._lock = threading.RLock()
        self._recs: dict[str, _EngineRec] = {}
        self._listeners: list[Callable[[str, EngineState], None]] = []
        self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
        self._closed = False
        self._watcher.start()

    def set_control(self, url: str, token: str = "") -> None:
        """Tell engines where the control plane (and its store API) lives.

        ``token`` is accepted for backward compatibility but unused: engines
        authenticate with per-engine tokens minted at create_engine, never
        the admin bearer token.
        """
        self.control_url = url

    def set_store_sock(self, uds_path: str) -> None:
        """Point engines at the native store's unix socket (binary protocol,
        bypasses HTTP for state ops); engines fall back to the HTTP store API
        when unset."""
        self.store_sock = uds_path

    # -- backend interface ----------------------------------------------
    def create_engine(self, agent: Agent, chips: tuple[int, ...]) -> str:
        engine_id = f"eng-{uuid.uuid4().hex[:12]}"
        port = _free_port()
        # Per-engine store credential: engines never see the admin token, and
        # the control plane validates this one against internal:token:{id}
        # (outside the namespace engines can reach).
        engine_token = uuid.uuid4().hex + uuid.uuid4().hex
        if self.store is not None:
            from ..store.schema import Keys

            self.store.set(Keys.internal_token(agent.id), engine_token)
        env = dict(os.environ)
        env.update(agent.env)
        env.update(
            {
                "AGENTAINER_AGENT_ID": agent.id,
                "AGENTAINER_AGENT_NAME": agent.name,
                "AGENTAINER_ENGINE": agent.model.engine,
                "AGENTAINER_MODEL_CONFIG": agent.model.config,
                "AGENTAINER_CHECKPOINT": agent.model.checkpoint,
                # engine tuning knobs (quant/max_batch/max_seq/…) ride the
                # same env channel the reference uses for container config
                "AGENTAINER_MODEL_OPTIONS": json.dumps(agent.model.options or {}),
                "AGENTAINER_PORT": str(port),
                "AGENTAINER_CHIPS": ",".join(map(str, chips)),
                "AGENTAINER_CONTROL_URL": self.control_url,
                "AGENTAINER_INTERNAL_TOKEN": engine_token,
                # shared persistent XLA cache: a respawned engine loads its
                # compiled executables instead of recompiling (recovery time)
                "AGENTAINER_COMPILE_CACHE": str(self._dir / "jax_cache"),
                # jax.profiler captures land here (POST /agents/{id}/profile)
                "AGENTAINER_PROFILE_DIR": str(self._dir / "profiles" / agent.id),
            }
        )
        from ..engine import is_tpu_engine

        if not is_tpu_engine(agent.model.engine):
            # non-TPU engines must not grab the TPU runtime — clear both the
            # platform selector and the axon-tunnel trigger the TPU-VM image
            # injects via sitecustomize
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        cmd = [self.python, "-m", "agentainer_tpu.runtime.engine_main"]
        rec = _EngineRec(
            engine_id=engine_id,
            agent_id=agent.id,
            port=port,
            cmd=cmd,
            env=env,
            chips=chips,
            auto_restart=agent.auto_restart,
            log_path=self._dir / "engines" / f"{engine_id}.log",
        )
        with self._lock:
            self._recs[engine_id] = rec
        return engine_id

    def start_engine(self, engine_id: str) -> None:
        with self._lock:
            rec = self._require(engine_id)
            if rec.proc is not None and rec.proc.poll() is None:
                rec.desired_running = True
                if self._probe(rec.port):
                    return  # genuinely alive and answering
                # poll() lies for a beat after a SIGKILL (exit status not
                # reapable yet) while the port already refuses: give the
                # kernel a moment to settle, then respawn if it's dead
                deadline = time.time() + 3.0
                while time.time() < deadline and rec.proc.poll() is None:
                    time.sleep(0.05)
                if rec.proc.poll() is None:
                    return  # alive but unresponsive: not ours to double-spawn
            self._spawn(rec)
            rec.desired_running = True
        self._wait_ready(rec)
        self._emit(engine_id, EngineState.RUNNING)

    def _spawn(self, rec: _EngineRec) -> None:
        if rec.log_file is not None:  # respawn: don't leak the old handle
            try:
                rec.log_file.close()
            except OSError:
                pass
        rec.log_file = open(rec.log_path, "ab")
        rec.env["AGENTAINER_CONTROL_URL"] = self.control_url
        rec.env["AGENTAINER_STORE_SOCK"] = self.store_sock
        rec.proc = subprocess.Popen(
            rec.cmd,
            env=rec.env,
            stdout=rec.log_file,
            stderr=subprocess.STDOUT,
            start_new_session=True,  # isolate signals from the daemon
        )
        rec.paused = False

    def _wait_ready(self, rec: _EngineRec) -> None:
        """Block until the engine answers /health (containers have no such
        gate in the reference; engines do because JAX init takes seconds and
        a 'started' engine should be servable)."""
        deadline = time.time() + self.ready_timeout_s
        while time.time() < deadline:
            if rec.proc is None or rec.proc.poll() is not None:
                raise RuntimeError(
                    f"engine {rec.engine_id} exited during startup; "
                    f"log: {self._tail_log(rec, 20)}"
                )
            if self._probe(rec.port, timeout=1.0):
                return
            time.sleep(0.05)
        raise RuntimeError(f"engine {rec.engine_id} not ready after {self.ready_timeout_s}s")

    def stop_engine(self, engine_id: str, timeout_s: float = 10.0) -> None:
        with self._lock:
            rec = self._require(engine_id)
            rec.desired_running = False
            proc = rec.proc
        if proc is None or proc.poll() is not None:
            return
        if rec.paused:
            try:
                os.killpg(proc.pid, signal.SIGCONT)
            except (ProcessLookupError, PermissionError):
                pass
            rec.paused = False
        try:
            proc.terminate()
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()  # hard kill after grace (agent.go:194 10s deadline)
            proc.wait(timeout=5)
        except ProcessLookupError:
            pass
        self._emit(engine_id, EngineState.EXITED)

    def pause_engine(self, engine_id: str) -> None:
        with self._lock:
            rec = self._require(engine_id)
            if rec.proc is None or rec.proc.poll() is not None:
                raise RuntimeError(f"engine {engine_id} not running")
            os.killpg(rec.proc.pid, signal.SIGSTOP)
            rec.paused = True
        self._emit(engine_id, EngineState.PAUSED)

    def resume_engine(self, engine_id: str) -> None:
        with self._lock:
            rec = self._require(engine_id)
            if rec.proc is None or rec.proc.poll() is not None:
                raise RuntimeError(f"engine {engine_id} not running")
            os.killpg(rec.proc.pid, signal.SIGCONT)
            rec.paused = False
        self._emit(engine_id, EngineState.RUNNING)

    def remove_engine(self, engine_id: str) -> None:
        with self._lock:
            rec = self._recs.pop(engine_id, None)
        if rec is None:
            return
        if rec.proc is not None and rec.proc.poll() is None:
            try:
                os.killpg(rec.proc.pid, signal.SIGKILL)
                rec.proc.wait(timeout=5)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass
        if rec.log_file is not None:
            try:
                rec.log_file.close()
            except OSError:
                pass

    def engine_info(self, engine_id: str) -> EngineInfo | None:
        with self._lock:
            rec = self._recs.get(engine_id)
            if rec is None:
                return None
            return EngineInfo(
                engine_id=engine_id,
                agent_id=rec.agent_id,
                state=self._state(rec),
                endpoint=f"http://127.0.0.1:{rec.port}",
                chips=rec.chips,
            )

    def _state(self, rec: _EngineRec) -> EngineState:
        if rec.proc is None:
            return EngineState.CREATED
        if rec.proc.poll() is not None:
            return EngineState.EXITED
        return EngineState.PAUSED if rec.paused else EngineState.RUNNING

    def list_engines(self) -> list[EngineInfo]:
        with self._lock:
            ids = list(self._recs)
        return [info for eid in ids if (info := self.engine_info(eid)) is not None]

    def logs(self, engine_id: str, tail: int = 100) -> list[str]:
        with self._lock:
            rec = self._recs.get(engine_id)
        if rec is None:
            return []
        return self._tail_log(rec, tail)

    def log_path(self, engine_id: str) -> str | None:
        """Filesystem path of the engine's log, for follow/streaming reads
        (agent.go:411-429 GetLogs(follow) parity — the server tails this)."""
        with self._lock:
            rec = self._recs.get(engine_id)
        return None if rec is None else str(rec.log_path)

    def _tail_log(self, rec: _EngineRec, tail: int) -> list[str]:
        try:
            with open(rec.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                lines = f.read().decode("utf-8", "replace").splitlines()
            return lines[-tail:]
        except OSError:
            return []

    def stats(self, engine_id: str) -> dict | None:
        """Pull serving counters from the engine's /metrics (the
        ContainerStats analogue, collector.go:228)."""
        with self._lock:
            rec = self._recs.get(engine_id)
            if rec is None or rec.proc is None or rec.proc.poll() is not None or rec.paused:
                return None
            port = rec.port
        import http.client
        import json as _json

        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2.0)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            data = _json.loads(resp.read()) if resp.status == 200 else None
            conn.close()
            return data
        except (OSError, ValueError):
            return None

    def probe_engine(self, engine_id: str) -> bool:
        """Real liveness: the engine answers /health. Process state alone
        lies for a beat after SIGKILL (poll() still None while the port
        already refuses) — resume() uses this to decide rehydration."""
        with self._lock:
            rec = self._recs.get(engine_id)
            if rec is None or rec.proc is None or rec.paused:
                return False
            port = rec.port
        return self._probe(port)

    @staticmethod
    def _probe(port: int, timeout: float = 2.0) -> bool:
        import http.client

        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
            conn.request("GET", "/health")
            ok = conn.getresponse().status == 200
            conn.close()
            return ok
        except OSError:
            return False

    def subscribe_events(self, callback: Callable[[str, EngineState], None]) -> Callable[[], None]:
        self._listeners.append(callback)

        def unsub() -> None:
            if callback in self._listeners:
                self._listeners.remove(callback)

        return unsub

    def _emit(self, engine_id: str, state: EngineState) -> None:
        for cb in list(self._listeners):
            try:
                cb(engine_id, state)
            except Exception:
                pass

    # -- restart-policy watcher (docker events + RestartPolicy analogue) --
    def _watch_loop(self) -> None:
        last: dict[str, EngineState] = {}
        while not self._closed:
            time.sleep(0.2)
            with self._lock:
                recs = list(self._recs.values())
            for rec in recs:
                state = self._state(rec)
                if last.get(rec.engine_id) != state:
                    if rec.engine_id in last:
                        self._emit(rec.engine_id, state)
                    last[rec.engine_id] = state
                if (
                    state == EngineState.EXITED
                    and rec.desired_running
                    and rec.auto_restart
                    and not self._closed
                ):
                    try:
                        with self._lock:
                            self._spawn(rec)
                            rec.restarts += 1
                        self._wait_ready(rec)
                        self._emit(rec.engine_id, EngineState.RUNNING)
                        last[rec.engine_id] = EngineState.RUNNING
                    except Exception:
                        rec.desired_running = False

    def close(self) -> None:
        self._closed = True
        with self._lock:
            ids = list(self._recs)
        for engine_id in ids:
            try:
                self.stop_engine(engine_id, timeout_s=2.0)
            except Exception:
                pass
            self.remove_engine(engine_id)

    def _require(self, engine_id: str) -> _EngineRec:
        rec = self._recs.get(engine_id)
        if rec is None:
            raise KeyError(f"no such engine: {engine_id}")
        return rec

    # -- test helper ------------------------------------------------------
    def kill_engine_hard(self, engine_id: str) -> None:
        """SIGKILL without touching desired state — a real crash."""
        with self._lock:
            rec = self._require(engine_id)
            if rec.proc is not None and rec.proc.poll() is None:
                os.killpg(rec.proc.pid, signal.SIGKILL)
                rec.proc.wait(timeout=5)
