"""Runtime backend interface — the Docker-daemon role, TPU-shaped.

In the reference the runtime is the Docker daemon: agents are containers the
control plane creates/starts/stops/pauses over the Docker socket
(reference internal/agent/agent.go:431-508, pkg/docker/client.go:10-28), and
the reconciler lists containers + watches the daemon event stream
(state_sync.go:253-309).

Here a Backend manages *engine processes* — model-serving programs bound to
TPU chips. The surface is deliberately the intersection the control plane
needs, so three implementations can sit behind it:

- ``FakeBackend``     in-memory, for unit tests (the fake the reference never
                      had, SURVEY.md §4),
- ``LocalBackend``    real subprocesses serving HTTP on localhost ports
                      (runtime/local.py) — the production path on a TPU-VM,
- future multi-host backends dispatching over DCN.

Engine states mirror container states (running/paused/created/exited) so the
reconciler's state mapping carries over (state_sync.go:216-229).
"""

from __future__ import annotations

import threading
import time
import uuid
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable

from ..core.spec import Agent


class EngineState(str, Enum):
    CREATED = "created"
    RUNNING = "running"
    PAUSED = "paused"
    EXITED = "exited"
    # crash-loop terminal state: the restart watcher gave up after the
    # rapid-death cap — the reconciler maps it to AgentStatus.FAILED, and
    # only an explicit start/resume re-arms the respawn policy
    FAILED = "failed"


@dataclass
class EngineInfo:
    engine_id: str
    agent_id: str
    state: EngineState
    endpoint: str = ""  # http URL the proxy forwards to ("" until started)
    chips: tuple[int, ...] = ()


class Backend(ABC):
    """Lifecycle operations over engine processes."""

    @abstractmethod
    def create_engine(
        self, agent: Agent, chips: tuple[int, ...], replica_index: int = 0
    ) -> str:
        """Create (but do not start) an engine; returns engine_id.

        ``replica_index`` distinguishes fleet replicas of the same agent:
        each replica must be its OWN failure domain (own process), so
        backends that pool same-model engines must key the pool per
        replica, never collapse two replicas into one process.

        Parity: container creation with labels/hostname/limits but no start
        (reference agent.go:431-508 createContainer).
        """

    @abstractmethod
    def start_engine(self, engine_id: str) -> None: ...

    @abstractmethod
    def stop_engine(self, engine_id: str, timeout_s: float = 10.0) -> None:
        """Graceful stop with the reference's 10s deadline (agent.go:194)."""

    @abstractmethod
    def pause_engine(self, engine_id: str) -> None: ...

    @abstractmethod
    def resume_engine(self, engine_id: str) -> None: ...

    @abstractmethod
    def remove_engine(self, engine_id: str) -> None: ...

    @abstractmethod
    def engine_info(self, engine_id: str) -> EngineInfo | None:
        """None if the engine is gone — the reconciler treats that like a
        vanished container (state_sync.go:169-187)."""

    @abstractmethod
    def list_engines(self) -> list[EngineInfo]: ...

    @abstractmethod
    def logs(self, engine_id: str, tail: int = 100) -> list[str]: ...

    def stats(self, engine_id: str) -> dict | None:
        """Resource/serving counters for the metrics plane (docker
        ContainerStats analogue, collector.go:228)."""
        return None

    def probe_engine(self, engine_id: str) -> bool:
        """Liveness beyond process state: does the engine actually answer?

        A SIGKILL'd process can report running for a beat (the exit status
        isn't reapable yet) while its socket already refuses connections —
        resume() must not trust engine_info alone or it no-ops on an agent
        that is mid-crash and returns success for a dead engine. Default:
        trust engine_info (backends without an HTTP surface).
        """
        info = self.engine_info(engine_id)
        return info is not None and info.state == EngineState.RUNNING

    def subscribe_events(self, callback: Callable[[str, EngineState], None]) -> Callable[[], None]:
        """Push-based engine state changes (docker event stream analogue).

        Default: no events; reconciler falls back to periodic polling, which
        the reference also keeps as belt-and-braces (state_sync.go:232-250).
        Returns an unsubscribe function.
        """
        return lambda: None

    def close(self) -> None:
        pass


class FakeBackend(Backend):
    """In-memory backend for tests: full state machine, injectable crashes."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._engines: dict[str, EngineInfo] = {}
        self._logs: dict[str, list[str]] = {}
        self._listeners: list[Callable[[str, EngineState], None]] = []
        self.start_delay_s = 0.0

    def _emit(self, engine_id: str, state: EngineState) -> None:
        for cb in list(self._listeners):
            try:
                cb(engine_id, state)
            except Exception:
                pass

    def create_engine(
        self, agent: Agent, chips: tuple[int, ...], replica_index: int = 0
    ) -> str:
        with self._lock:
            engine_id = f"eng-{uuid.uuid4().hex[:12]}"
            self._engines[engine_id] = EngineInfo(
                engine_id=engine_id,
                agent_id=agent.id,
                state=EngineState.CREATED,
                # the engine id rides the endpoint so the proxy's fake://
                # dispatch reaches the ROUTED replica, not always the primary
                endpoint=f"fake://{agent.id}/{engine_id}",
                chips=chips,
            )
            self._logs[engine_id] = [f"created engine for {agent.id} on chips {chips}"]
            return engine_id

    def start_engine(self, engine_id: str) -> None:
        if self.start_delay_s:
            time.sleep(self.start_delay_s)
        with self._lock:
            info = self._require(engine_id)
            info.state = EngineState.RUNNING
            self._logs[engine_id].append("started")
        self._emit(engine_id, EngineState.RUNNING)

    def stop_engine(self, engine_id: str, timeout_s: float = 10.0) -> None:
        with self._lock:
            info = self._require(engine_id)
            info.state = EngineState.EXITED
            self._logs[engine_id].append("stopped")
        self._emit(engine_id, EngineState.EXITED)

    def pause_engine(self, engine_id: str) -> None:
        with self._lock:
            info = self._require(engine_id)
            if info.state != EngineState.RUNNING:
                raise RuntimeError(f"engine {engine_id} not running")
            info.state = EngineState.PAUSED
        self._emit(engine_id, EngineState.PAUSED)

    def resume_engine(self, engine_id: str) -> None:
        with self._lock:
            info = self._require(engine_id)
            if info.state != EngineState.PAUSED:
                raise RuntimeError(f"engine {engine_id} not paused")
            info.state = EngineState.RUNNING
        self._emit(engine_id, EngineState.RUNNING)

    def remove_engine(self, engine_id: str) -> None:
        with self._lock:
            self._engines.pop(engine_id, None)
            self._logs.pop(engine_id, None)

    def engine_info(self, engine_id: str) -> EngineInfo | None:
        with self._lock:
            return self._engines.get(engine_id)

    def list_engines(self) -> list[EngineInfo]:
        with self._lock:
            return list(self._engines.values())

    def logs(self, engine_id: str, tail: int = 100) -> list[str]:
        with self._lock:
            return self._logs.get(engine_id, [])[-tail:]

    def subscribe_events(self, callback: Callable[[str, EngineState], None]) -> Callable[[], None]:
        self._listeners.append(callback)

        def unsub() -> None:
            if callback in self._listeners:
                self._listeners.remove(callback)

        return unsub

    def handle_request(
        self, engine_id: str, method: str, path: str, headers: dict, body: bytes
    ) -> tuple[int, dict[str, str], bytes]:
        """In-process request dispatch for ``fake://`` endpoints.

        Raises ConnectionError when the engine is not running — the analogue
        of connection-refused against a dead container, which the proxy's
        crash heuristic keys on (reference server.go:597-606).
        """
        import json as _json

        with self._lock:
            info = self._engines.get(engine_id)
            if info is None or info.state != EngineState.RUNNING:
                raise ConnectionError(f"engine {engine_id} not running")
        route = path.split("?")[0]
        if route == "/health":
            return 200, {"Content-Type": "application/json"}, b'{"status":"healthy"}'
        payload = {
            "echo": {
                "method": method,
                "path": path,
                "body": body.decode("utf-8", "replace"),
            }
        }
        return 200, {"Content-Type": "application/json"}, _json.dumps(payload).encode()

    # -- test helpers ----------------------------------------------------
    def crash_engine(self, engine_id: str) -> None:
        """Simulate a hard crash (container OOM-kill analogue)."""
        with self._lock:
            info = self._require(engine_id)
            info.state = EngineState.EXITED
            self._logs[engine_id].append("crashed")
        self._emit(engine_id, EngineState.EXITED)

    def vanish_engine(self, engine_id: str) -> None:
        """Simulate the engine record disappearing entirely (docker rm -f)."""
        with self._lock:
            self._engines.pop(engine_id, None)

    def _require(self, engine_id: str) -> EngineInfo:
        info = self._engines.get(engine_id)
        if info is None:
            raise KeyError(f"no such engine: {engine_id}")
        return info
