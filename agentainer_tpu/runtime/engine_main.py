"""Engine subprocess entry point: ``python -m agentainer_tpu.runtime.engine_main``.

The analogue of a container's CMD (reference examples/gpt-agent/Dockerfile
runs gunicorn app:app). The LocalBackend spawns this with the agent's
identity, port, chip assignment, and control-plane URL in the environment.
Engine selection stays lazy so the echo engine never imports JAX.
"""

from __future__ import annotations

import logging
import os
import sys


def main() -> None:
    # request lines (aiohttp.access) and engine warnings go to stdout, which
    # the backend captures into the engine's log file — the same visibility
    # a container gets from docker logs (agent.go:411-429 / logs --follow)
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stdout,
        format="%(asctime)s %(name)s %(message)s",
        force=True,
    )
    # fault plane: engine-side failpoints (engine.*, store_client.rpc) arm
    # from the env the daemon exported; unset = registry empty = no-ops
    if os.environ.get("ATPU_FAULTS"):
        from .. import faults

        faults.arm_from_env()
    engine = os.environ.get("AGENTAINER_ENGINE", "echo")
    from ..engine import is_tpu_engine

    if is_tpu_engine(engine):
        # Honor JAX_PLATFORMS for real: the TPU-VM image's sitecustomize
        # pre-imports jax pinned to the tunnel backend, so the env var alone
        # is ignored by the time engine code runs — jax.config.update is
        # what actually selects the platform (same trick as
        # tests/conftest.py). A CPU-pinned control plane must spawn CPU
        # engines, not engines that block on the one TPU session.
        plat = os.environ.get("JAX_PLATFORMS", "")
        if plat:
            import jax

            jax.config.update("jax_platforms", plat)
        # Persistent XLA compilation cache (runtime/local.py points this at
        # the daemon's data dir): a restarted engine reloads its compiled
        # decode/prefill executables instead of recompiling, which is most
        # of what crash-replay recovery time is made of on a 1-core host.
        cache_dir = os.environ.get("AGENTAINER_COMPILE_CACHE", "")
        if cache_dir:
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # Multi-host: the ENGINE processes are the ones running JAX compute,
        # so they are what joins the jax.distributed cluster (one TPU engine
        # per host, ATPU_DIST_* set by the operator/scheduler). The control
        # plane never blocks on the cluster barrier.
        from ..parallel.dcn import init_distributed

        try:
            init_distributed()
        except Exception as e:
            # Loud failure (ADVICE r3): an engine explicitly configured to
            # join a multi-host cluster must not silently serve a local-only
            # topology the operator believes spans hosts.
            print(f"[engine] jax.distributed init failed: {e}", file=sys.stderr)
            sys.exit(3)
    import importlib

    from ..engine import engine_registry

    module = engine_registry().get(engine)
    if module is None:
        print(f"unknown engine {engine!r}", file=sys.stderr)
        sys.exit(2)
    importlib.import_module(module).serve()


if __name__ == "__main__":
    main()
