"""Engine subprocess entry point: ``python -m agentainer_tpu.runtime.engine_main``.

The analogue of a container's CMD (reference examples/gpt-agent/Dockerfile
runs gunicorn app:app). The LocalBackend spawns this with the agent's
identity, port, chip assignment, and control-plane URL in the environment.
Engine selection stays lazy so the echo engine never imports JAX.
"""

from __future__ import annotations

import logging
import os
import sys


def main() -> None:
    # request lines (aiohttp.access) and engine warnings go to stdout, which
    # the backend captures into the engine's log file — the same visibility
    # a container gets from docker logs (agent.go:411-429 / logs --follow)
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stdout,
        format="%(asctime)s %(name)s %(message)s",
        force=True,
    )
    engine = os.environ.get("AGENTAINER_ENGINE", "echo")
    if engine == "echo":
        from ..engine.echo import serve

        serve()
    elif engine == "llm":
        from ..engine.llm_serve import serve

        serve()
    else:
        print(f"unknown engine {engine!r}", file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    main()
