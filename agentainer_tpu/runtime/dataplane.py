"""Python handle for the native data plane (native/dataplane.cc).

The daemon starts the C++ listener on the public port; Python keeps policy
(lifecycle, replay, health) and feeds the routing table on every agent
mutation. Agent traffic then flows entirely on native threads: journal →
engine dispatch → settle, with zero Python in the loop. Management paths are
transparently forwarded to the aiohttp server on its internal port.
"""

from __future__ import annotations

import ctypes
from urllib.parse import urlparse

from ..native import load


class NativeDataPlane:
    def __init__(
        self,
        store,  # NativeStore — shares its C handle with the listener
        listen_host: str,
        listen_port: int,
        backend_host: str,
        backend_port: int,
        uds_path: str = "",
    ):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._store = store  # keep alive: dp threads use its handle
        self._handle = self._lib.atpu_dp_start(
            store.handle,
            listen_host.encode(),
            listen_port,
            backend_host.encode(),
            backend_port,
            uds_path.encode() if uds_path else None,
        )
        if not self._handle:
            raise RuntimeError(f"data plane failed to bind port {listen_port}")
        self.uds_path = uds_path

    @property
    def port(self) -> int:
        return self._lib.atpu_dp_port(self._handle)

    def route_set(
        self, agent_id: str, endpoint: str | None, status: str, persist: bool
    ) -> None:
        """Update an agent's route. ``endpoint`` is the engine URL
        (http://127.0.0.1:PORT) or None when no engine is live."""
        host, port = "127.0.0.1", 0
        if endpoint:
            u = urlparse(endpoint)
            host, port = u.hostname or "127.0.0.1", u.port or 80
        self._lib.atpu_dp_route_set(
            self._handle,
            agent_id.encode(),
            host.encode(),
            port,
            status.encode(),
            1 if persist else 0,
        )

    def route_del(self, agent_id: str) -> None:
        self._lib.atpu_dp_route_del(self._handle, agent_id.encode())

    def counters_drain(self, agent_id: str) -> dict:
        requests = ctypes.c_uint64()
        lat_sum = ctypes.c_double()
        lat_max = ctypes.c_double()
        self._lib.atpu_dp_counters_drain(
            self._handle,
            agent_id.encode(),
            ctypes.byref(requests),
            ctypes.byref(lat_sum),
            ctypes.byref(lat_max),
        )
        return {
            "requests": requests.value,
            "latency_sum": lat_sum.value,
            "latency_max": lat_max.value,
        }

    def stop(self) -> None:
        if self._handle:
            self._lib.atpu_dp_stop(self._handle)
            self._handle = None
