"""Slice scheduler — chip/HBM placement for agents.

No reference counterpart: the reference's "placement" is Docker putting every
container on one host's bridge network with optional NanoCPU/memory caps
(agent.go:482-508). Here, placement is the core TPU question: which chips of
the slice an agent's engine binds, and how much HBM it may claim for weights
+ KV. The scheduler is the source of the device mesh each engine builds.

Model: a slice is ``total_chips`` chips (e.g. v5e-8) with ``hbm_per_chip``
bytes each (16 GiB on v5e), laid out as a 2-D mesh (v5e-8 is 2×4). An
allocation is an ICI-adjacent sub-rectangle of that grid, so TP/ring
collectives ride physical neighbor links. Weight-sharing groups let several agents
serving the same model config co-locate on the same chips and count the
weight bytes once (the multi-agent HBM-sharing feature of BASELINE.json
config #4).

Allocations are persisted at ``slices:allocations`` so a restarted control
plane reconciles placement instead of double-booking chips.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..core.errors import ResourceExhausted
from ..core.spec import Agent
from ..store.base import Store
from ..store.schema import Keys

HBM_PER_CHIP_V5E = 16 * 1024**3


@dataclass
class Placement:
    agent_id: str
    chips: tuple[int, ...]
    hbm_bytes: int
    share_group: str = ""  # e.g. model config name when weights are shared

    def to_dict(self) -> dict:
        return {
            "agent_id": self.agent_id,
            "chips": list(self.chips),
            "hbm_bytes": self.hbm_bytes,
            "share_group": self.share_group,
        }

    @staticmethod
    def from_dict(d: dict) -> "Placement":
        return Placement(
            agent_id=d["agent_id"],
            chips=tuple(d["chips"]),
            hbm_bytes=int(d["hbm_bytes"]),
            share_group=d.get("share_group", ""),
        )


@dataclass
class SliceTopology:
    """A TPU slice as a 2-D chip grid.

    v5e-8 is physically a 2×4 mesh, not a ring — "adjacent" means
    neighboring in the grid, and an ICI-efficient allocation is a
    sub-RECTANGLE of it (round-1's 1-D "contiguous id run" model called
    chips 3 and 4 neighbors; on the real 2×4 grid they're in different
    rows). Chip ids are row-major over ``mesh_shape``.
    """

    total_chips: int = 8
    hbm_per_chip: int = HBM_PER_CHIP_V5E
    name: str = "v5e-8"
    mesh_shape: tuple[int, int] = (2, 4)  # (rows, cols)
    # multi-host slices (e.g. v5e-16 = 2 hosts × 8 chips): chip ids are
    # row-major with each host owning a contiguous run; placements that fit
    # one host stay on ICI, cross-host spans pay DCN (parallel/dcn.py)
    hosts: int = 1

    def __post_init__(self) -> None:
        rows, cols = self.mesh_shape
        if rows * cols != self.total_chips:
            # derive the squarest grid for the chip count (the shape daemon
            # configs omit): 8→2×4, 16→4×4, 4→2×2; primes degenerate to a row
            r = max(d for d in range(1, int(self.total_chips**0.5) + 1)
                    if self.total_chips % d == 0)
            self.mesh_shape = (r, self.total_chips // r)
        if self.hosts < 1 or self.total_chips % self.hosts:
            raise ValueError(
                f"hosts={self.hosts} must divide total_chips={self.total_chips}"
            )

    @property
    def chips_per_host(self) -> int:
        return self.total_chips // self.hosts

    def host_of(self, chip: int) -> int:
        return chip // self.chips_per_host

    def spans_hosts(self, chips: tuple[int, ...]) -> bool:
        return len({self.host_of(c) for c in chips}) > 1

    def windows(self, n: int) -> list[tuple[int, ...]]:
        """Candidate ICI-adjacent chip sets of size n, preference-ordered.

        Sub-rectangles of the grid (squarer first — shorter worst-case
        ICI hop for TP all-reduces / ring collectives), deduplicated. If
        no h×w rectangle has area n (e.g. n=3 on 2×4 → the 1×3 row run IS
        a rectangle; n=5 has none), fall back to row-major id runs so odd
        requests still place (with a wraparound hop the caller accepted
        by asking for a non-rectangular count)."""
        rows, cols = self.mesh_shape
        shapes = [
            (h, w)
            for h in range(1, rows + 1)
            for w in range(1, cols + 1)
            if h * w == n
        ]
        shapes.sort(key=lambda s: (max(s), s[0]))
        out: list[tuple[int, ...]] = []
        seen: set[tuple[int, ...]] = set()
        for h, w in shapes:
            for r in range(rows - h + 1):
                for c in range(cols - w + 1):
                    win = tuple(
                        sorted(
                            rr * cols + cc
                            for rr in range(r, r + h)
                            for cc in range(c, c + w)
                        )
                    )
                    if win not in seen:
                        seen.add(win)
                        out.append(win)
        if not out:
            out = [
                tuple(range(s, s + n)) for s in range(self.total_chips - n + 1)
            ]
        # host-aware preference: windows inside one host's ICI domain rank
        # ahead of ones whose collectives would cross DCN (stable sort
        # keeps the squareness ordering within each class)
        if self.hosts > 1:
            out.sort(key=self.spans_hosts)
        return out


class SliceScheduler:
    """First-fit contiguous chip allocator with per-chip HBM accounting."""

    def __init__(self, store: Store, topology: SliceTopology | None = None):
        self._store = store
        self.topology = topology or SliceTopology()
        self._lock = threading.RLock()
        self._placements: dict[str, Placement] = {}
        self._load()

    # -- persistence -----------------------------------------------------
    def _load(self) -> None:
        raw = self._store.get_json(Keys.SLICE_ALLOCATIONS)
        if raw:
            self._placements = {p["agent_id"]: Placement.from_dict(p) for p in raw}

    def _save(self) -> None:
        self._store.set_json(
            Keys.SLICE_ALLOCATIONS, [p.to_dict() for p in self._placements.values()]
        )

    # -- accounting ------------------------------------------------------
    def _chip_usage(self) -> dict[int, int]:
        """HBM bytes claimed per chip, counting each share group's weights once.

        Within a share group, every member ships the same weights, so the
        group's HBM claim per chip is max(member claims), not the sum.
        """
        by_group: dict[str, list[Placement]] = {}
        solo: list[Placement] = []
        for p in self._placements.values():
            if p.share_group:
                by_group.setdefault(p.share_group, []).append(p)
            else:
                solo.append(p)
        usage: dict[int, int] = {c: 0 for c in range(self.topology.total_chips)}
        for p in solo:
            per_chip = p.hbm_bytes // max(1, len(p.chips))
            for c in p.chips:
                usage[c] += per_chip
        for group in by_group.values():
            chips: set[int] = set()
            for p in group:
                chips.update(p.chips)
            per_chip = max(p.hbm_bytes // max(1, len(p.chips)) for p in group)
            for c in chips:
                usage[c] += per_chip
        return usage

    # -- API -------------------------------------------------------------
    def allocate(self, agent: Agent, share_group: str = "") -> Placement:
        with self._lock:
            if agent.id in self._placements:
                return self._placements[agent.id]
            n = max(1, agent.resources.chips)
            if n > self.topology.total_chips:
                raise ResourceExhausted(
                    f"requested {n} chips but slice {self.topology.name} has "
                    f"{self.topology.total_chips}"
                )
            need_per_chip = agent.resources.hbm_bytes // n
            usage = self._chip_usage()

            # Weight sharing: prefer the chips the share group already owns —
            # but only if raising the group's per-chip claim still fits
            # (usage already counts the group at its current max).
            if share_group:
                members = [p for p in self._placements.values() if p.share_group == share_group]
                group_chips = sorted({c for p in members for c in p.chips})
                if len(group_chips) >= n:
                    chips = tuple(group_chips[:n])
                    current_claim = max(
                        (p.hbm_bytes // max(1, len(p.chips)) for p in members), default=0
                    )
                    delta = max(0, need_per_chip - current_claim)
                    if all(usage[c] + delta <= self.topology.hbm_per_chip for c in chips):
                        placement = Placement(
                            agent.id, chips, agent.resources.hbm_bytes, share_group
                        )
                        self._placements[agent.id] = placement
                        self._save()
                        return placement
                    # group chips can't absorb the larger claim: place solo
                    # (weights not shared rather than silently overcommitted)
                    share_group = ""

            # First-fit over ICI-adjacent windows (sub-rectangles of the
            # 2-D chip grid, squarer first — see SliceTopology.windows).
            for window in self.topology.windows(n):
                if all(usage[c] + need_per_chip <= self.topology.hbm_per_chip for c in window):
                    placement = Placement(agent.id, window, agent.resources.hbm_bytes, share_group)
                    self._placements[agent.id] = placement
                    self._save()
                    return placement
            raise ResourceExhausted(
                f"no ICI-adjacent {n}-chip window with {need_per_chip} B free HBM per chip "
                f"on {self.topology.name} ({self.topology.mesh_shape[0]}x"
                f"{self.topology.mesh_shape[1]} mesh)"
            )

    def release(self, agent_id: str) -> None:
        with self._lock:
            if self._placements.pop(agent_id, None) is not None:
                self._save()

    def placement(self, agent_id: str) -> Placement | None:
        with self._lock:
            return self._placements.get(agent_id)

    def placements(self) -> list[Placement]:
        with self._lock:
            return list(self._placements.values())

    def free_hbm(self) -> dict[int, int]:
        with self._lock:
            usage = self._chip_usage()
            return {c: self.topology.hbm_per_chip - u for c, u in usage.items()}
