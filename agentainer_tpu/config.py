"""Layered daemon configuration.

Parity with the reference's viper config (internal/config/config.go:49-107):
YAML file searched in ``.``, ``~/.agentainer_tpu``, ``/etc/agentainer_tpu``;
environment overrides with an ``ATPU_`` prefix; defaults matching the
reference's envelope (server on :8081, static bearer token, request
persistence on). TPU additions: store URL (mem:// by default — no Redis
sidecar needed on a TPU-VM) and the slice topology the scheduler manages.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path

import yaml

DEFAULT_TOKEN = "agentainer-default-token"  # config.go:66 parity


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 8081


@dataclass
class SliceConfig:
    total_chips: int = 8
    hbm_per_chip: int = 16 * 1024**3
    name: str = "v5e-8"
    hosts: int = 1  # multi-host slices: chips split evenly across hosts


@dataclass
class FeatureFlags:
    request_persistence: bool = True  # config.go:70
    auto_restart_default: bool = False
    # Serve /agent/* + the engine store socket from the C++ data plane when
    # the native library is available (falls back to the aiohttp proxy).
    native_dataplane: bool = True
    # Default for engines' self-speculative decoding (prompt-lookup drafts
    # + batched verify). Per-deployment model options override; false here
    # pins the whole fleet to the plain decode path (the A/B baseline).
    speculative: bool = True
    # Default for engines' paged KV arena (block tables: pool-bounded
    # resident sessions, zero-copy prefix sharing, page-tail speculative
    # rewind). Off by default while the dense arena remains the
    # hardware-burned-in baseline; per-deployment model options override
    # (same plumbing pattern as ``speculative``).
    paged_kv: bool = False
    # Fleet defaults for the remaining engine A/B options, completing the
    # feature-flag quad (engine kwarg <-> deploy CLI flag <-> YAML options
    # <-> ATPU_* env — machine-checked by analysis rule ATP006):
    # admission-aware decode chunking and the cross-session prefix arena.
    adaptive_decode: bool = True
    prefix_cache: bool = True
    # Default for engines' fused on-device decode loop (multi-step
    # lax.while_loop with in-loop sampling, per-lane early exit, and one
    # readback per loop). Off by default while the per-chunk dispatch
    # remains the A/B baseline; per-deployment model options override.
    fused_decode: bool = False
    # Default for engines' in-loop device speculation: the fused loop's
    # n-gram drafter + batched verify branch, replacing the host-side
    # prompt-lookup round-trip while a lane stays loop-resident. On by
    # default — it only engages when the engine is fused+speculative and
    # unmeshed, and greedy lanes are bit-exact with the host drafter.
    inloop_spec: bool = True
    # Default for engines' segmented approx top-k sampler
    # (jax.lax.approx_max_k over a fixed-width segment instead of the
    # full-vocab sort). Off by default: the exact shared-sort sampler is
    # the baseline; approx is opt-in and NOT bit-exact for sampled lanes.
    approx_topk: bool = False
    # Default for the tiered KV hierarchy (device → pinned host RAM →
    # store): idle sessions park off-device and promote back at their
    # next turn, with pool-pressure demotion converting 429s into
    # slower-but-served admissions. Off by default — tiering is the
    # opt-in density lever; the resident-only arena is the A/B baseline.
    kv_tiering: bool = False
    # Proxy-side park linger: seconds an idle session must stay silent
    # after its response settles before the proxy parks it off-device.
    # Sized to agentic tool-call gaps — a tool round-trip inside the
    # linger cancels the park; anything longer pays one prewarm instead.
    tier_park_linger_s: float = 1.0
    # Default for SSE token streaming (stream=true on /chat): the proxy
    # forwards the engine's event stream with every offset journaled as a
    # streaming checkpoint, so a mid-stream crash fails over gaplessly.
    # Off by default — the buffered response path is the A/B baseline and
    # stays byte-identical while this is off.
    streaming: bool = False


@dataclass
class DeadlineConfig:
    """End-to-end request deadlines + overload shedding.

    ``enabled: false`` preserves the pre-deadline behavior everywhere
    (no default deadline, no shedding, no disconnect propagation) — the
    A/B baseline. Watermarks are depth thresholds at which the proxy
    answers ``429 + Retry-After`` instead of journaling more work that
    will expire unserved."""

    enabled: bool = True
    # default per-request budget when the caller sends no
    # X-Agentainer-Deadline-Ms header; 0 = no default deadline
    default_ms: float = 30000.0
    # per-agent pending-journal depth that starts shedding (0 = off)
    shed_pending_per_agent: int = 64
    # global pending ceiling across every agent (0 = off)
    shed_pending_global: int = 512
    # engine queue+waiting depth (from the latest metrics sample) that
    # starts shedding for that agent (0 = don't consult engine depth)
    engine_queue_watermark: int = 0
    # Retry-After seconds on shed responses
    retry_after_s: float = 1.0


@dataclass
class FleetConfig:
    """Replica fleet: N engine replicas per agent behind the routing tier.

    ``replicas: 1`` (the default) is the pre-fleet behavior exactly — one
    engine per agent, no routing tier, no lease monitor traffic — and is
    the A/B baseline. With N > 1 each replica is its own failure domain
    (own process, own port, own crash-loop watcher); sessions are routed
    with KV-residency affinity, fresh sessions by power-of-two-choices on
    in-flight depth, and a dead replica's sessions fail over to a survivor
    via the store-durable KV snapshot (token-identical resume). Per-deploy
    ``replicas`` in the agent body overrides the fleet default."""

    replicas: int = 1
    # replica heartbeat lease: the monitor probes each replica every
    # lease_interval_s and refreshes a store lease with lease_ttl_s; a
    # replica whose lease is older than suspect_after_s is SUSPECT
    # (excluded from routing), older than dead_after_s is DEAD (repaired)
    lease_ttl_s: float = 6.0
    lease_interval_s: float = 1.0
    suspect_after_s: float = 3.0
    dead_after_s: float = 6.0
    # bounded cross-replica retry for connection-level dispatch failures
    # (nothing executed on the dead replica, and the journal CAS admits
    # exactly one dispatcher, so the retry cannot double-execute)
    retry_next_replica: int = 2
    # per-replica circuit breaker (one bad replica must not open a breaker
    # for the whole agent)
    breaker_failures: int = 3
    breaker_cooldown_s: float = 2.0


@dataclass
class ResilienceConfig:
    """Crash-loop backoff, store-outage degradation, and fault injection.

    The backoff knobs govern the local backend's restart watcher: a
    crashed engine respawns immediately once, then with exponential delay
    (``restart_backoff_base_s`` doubling up to ``restart_backoff_max_s``);
    an incarnation that dies within ``restart_window_s`` of its spawn
    counts as a *rapid* death, and after ``restart_max_rapid`` of those in
    a row the agent lands FAILED with a recorded reason instead of
    hot-looping forever. The breaker knobs govern the proxy's store
    circuit breaker (503 + Retry-After instead of hanging on a dead
    store); the store_retry knobs govern the engine store client's bounded
    retry. ``faults`` is a failpoint arming spec (agentainer_tpu/faults.py
    grammar) applied at daemon startup — empty (the default) means the
    fault plane is entirely disarmed and zero-overhead."""

    restart_backoff_base_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    restart_window_s: float = 30.0
    restart_max_rapid: int = 5
    store_retries: int = 3
    store_retry_base_s: float = 0.05
    breaker_failures: int = 5
    breaker_cooldown_s: float = 2.0
    faults: str = ""


@dataclass
class Cadences:
    """Background-loop intervals, reference values (BASELINE.md)."""

    state_sync_s: float = 10.0  # main.go:325
    replay_scan_s: float = 5.0  # replay_worker.go:37
    health_interval_s: float = 30.0  # monitor.go:119
    metrics_interval_s: float = 10.0  # collector.go:205


@dataclass
class Config:
    server: ServerConfig = field(default_factory=ServerConfig)
    slice: SliceConfig = field(default_factory=SliceConfig)
    features: FeatureFlags = field(default_factory=FeatureFlags)
    cadences: Cadences = field(default_factory=Cadences)
    deadlines: DeadlineConfig = field(default_factory=DeadlineConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    auth_token: str = DEFAULT_TOKEN
    # "auto": native C++ store with AOF durability when the library builds,
    # in-memory store otherwise. Explicit: mem:// | native://[aof-path]
    store_url: str = "auto"
    data_dir: str = "~/.agentainer_tpu"

    @property
    def data_path(self) -> Path:
        return Path(os.path.expanduser(self.data_dir))


_SEARCH_PATHS = [".", "~/.agentainer_tpu", "/etc/agentainer_tpu"]


def load_config(path: str | None = None) -> Config:
    cfg = Config()
    doc: dict = {}
    candidates = [path] if path else [os.path.join(os.path.expanduser(p), "config.yaml") for p in _SEARCH_PATHS]
    for cand in candidates:
        if cand and os.path.isfile(cand):
            with open(cand) as f:
                doc = yaml.safe_load(f) or {}
            break

    server = doc.get("server", {})
    cfg.server.host = server.get("host", cfg.server.host)
    cfg.server.port = int(server.get("port", cfg.server.port))
    sl = doc.get("slice", {})
    cfg.slice.total_chips = int(sl.get("total_chips", cfg.slice.total_chips))
    cfg.slice.hbm_per_chip = int(sl.get("hbm_per_chip", cfg.slice.hbm_per_chip))
    cfg.slice.name = sl.get("name", cfg.slice.name)
    cfg.slice.hosts = int(sl.get("hosts", cfg.slice.hosts))
    feats = doc.get("features", {})
    cfg.features.request_persistence = bool(
        feats.get("request_persistence", cfg.features.request_persistence)
    )
    dl = doc.get("deadlines", {})
    cfg.deadlines.enabled = bool(dl.get("enabled", cfg.deadlines.enabled))
    cfg.deadlines.default_ms = float(dl.get("default_ms", cfg.deadlines.default_ms))
    cfg.deadlines.shed_pending_per_agent = int(
        dl.get("shed_pending_per_agent", cfg.deadlines.shed_pending_per_agent)
    )
    cfg.deadlines.shed_pending_global = int(
        dl.get("shed_pending_global", cfg.deadlines.shed_pending_global)
    )
    cfg.deadlines.engine_queue_watermark = int(
        dl.get("engine_queue_watermark", cfg.deadlines.engine_queue_watermark)
    )
    cfg.deadlines.retry_after_s = float(
        dl.get("retry_after_s", cfg.deadlines.retry_after_s)
    )
    res = doc.get("resilience", {})
    cfg.resilience.restart_backoff_base_s = float(
        res.get("restart_backoff_base_s", cfg.resilience.restart_backoff_base_s)
    )
    cfg.resilience.restart_backoff_max_s = float(
        res.get("restart_backoff_max_s", cfg.resilience.restart_backoff_max_s)
    )
    cfg.resilience.restart_window_s = float(
        res.get("restart_window_s", cfg.resilience.restart_window_s)
    )
    cfg.resilience.restart_max_rapid = int(
        res.get("restart_max_rapid", cfg.resilience.restart_max_rapid)
    )
    cfg.resilience.store_retries = int(
        res.get("store_retries", cfg.resilience.store_retries)
    )
    cfg.resilience.store_retry_base_s = float(
        res.get("store_retry_base_s", cfg.resilience.store_retry_base_s)
    )
    cfg.resilience.breaker_failures = int(
        res.get("breaker_failures", cfg.resilience.breaker_failures)
    )
    cfg.resilience.breaker_cooldown_s = float(
        res.get("breaker_cooldown_s", cfg.resilience.breaker_cooldown_s)
    )
    cfg.resilience.faults = str(res.get("faults", cfg.resilience.faults))
    fl = doc.get("fleet", {})
    cfg.fleet.replicas = int(fl.get("replicas", cfg.fleet.replicas))
    cfg.fleet.lease_ttl_s = float(fl.get("lease_ttl_s", cfg.fleet.lease_ttl_s))
    cfg.fleet.lease_interval_s = float(
        fl.get("lease_interval_s", cfg.fleet.lease_interval_s)
    )
    cfg.fleet.suspect_after_s = float(
        fl.get("suspect_after_s", cfg.fleet.suspect_after_s)
    )
    cfg.fleet.dead_after_s = float(fl.get("dead_after_s", cfg.fleet.dead_after_s))
    cfg.fleet.retry_next_replica = int(
        fl.get("retry_next_replica", cfg.fleet.retry_next_replica)
    )
    cfg.fleet.breaker_failures = int(
        fl.get("breaker_failures", cfg.fleet.breaker_failures)
    )
    cfg.fleet.breaker_cooldown_s = float(
        fl.get("breaker_cooldown_s", cfg.fleet.breaker_cooldown_s)
    )
    sec = doc.get("security", {})
    cfg.auth_token = sec.get("auth_token", cfg.auth_token)
    cfg.store_url = doc.get("store", {}).get("url", cfg.store_url)
    cfg.data_dir = doc.get("data_dir", cfg.data_dir)

    # Env overrides, explicit binds like the reference's AGENTAINER_* set
    # (config.go:72-81).
    env = os.environ
    cfg.server.host = env.get("ATPU_SERVER_HOST", cfg.server.host)
    cfg.server.port = int(env.get("ATPU_SERVER_PORT", cfg.server.port))
    cfg.auth_token = env.get("ATPU_AUTH_TOKEN", cfg.auth_token)
    cfg.store_url = env.get("ATPU_STORE_URL", cfg.store_url)
    cfg.data_dir = env.get("ATPU_DATA_DIR", cfg.data_dir)
    if "ATPU_SLICE_CHIPS" in env:
        cfg.slice.total_chips = int(env["ATPU_SLICE_CHIPS"])
    if "ATPU_SLICE_HOSTS" in env:
        cfg.slice.hosts = int(env["ATPU_SLICE_HOSTS"])
    if "ATPU_DEADLINES" in env:
        cfg.deadlines.enabled = env["ATPU_DEADLINES"].lower() in ("1", "true", "yes")
    if "ATPU_DEADLINE_DEFAULT_MS" in env:
        cfg.deadlines.default_ms = float(env["ATPU_DEADLINE_DEFAULT_MS"])
    if "ATPU_SHED_PER_AGENT" in env:
        cfg.deadlines.shed_pending_per_agent = int(env["ATPU_SHED_PER_AGENT"])
    if "ATPU_SHED_GLOBAL" in env:
        cfg.deadlines.shed_pending_global = int(env["ATPU_SHED_GLOBAL"])
    if "ATPU_REQUEST_PERSISTENCE" in env:
        cfg.features.request_persistence = env["ATPU_REQUEST_PERSISTENCE"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.native_dataplane = bool(
        feats.get("native_dataplane", cfg.features.native_dataplane)
    )
    if "ATPU_NATIVE_DATAPLANE" in env:
        cfg.features.native_dataplane = env["ATPU_NATIVE_DATAPLANE"].lower() in (
            "1",
            "true",
            "yes",
        )
    if "ATPU_FLEET_REPLICAS" in env:
        # the env bind completes the fleet flag's operator surface
        # (config.yaml `fleet.replicas` / per-deploy `replicas` / env):
        # malformed values fall back like the other numeric binds
        try:
            cfg.fleet.replicas = int(env["ATPU_FLEET_REPLICAS"])
        except ValueError:
            pass
    if "ATPU_FAULTS" in env:
        # the env spec REPLACES a config-file spec rather than merging:
        # an operator arming from the shell must get exactly that schedule
        cfg.resilience.faults = env["ATPU_FAULTS"]

    def _env_num(name: str, cast, current):
        # malformed resilience numbers fall back to the config value
        # instead of refusing to boot (LocalBackend reads the same vars
        # with the same tolerance — behavior must not depend on which
        # reader hits them first)
        raw = env.get(name)
        if raw is None:
            return current
        try:
            return cast(raw)
        except ValueError:
            return current

    res_cfg = cfg.resilience
    res_cfg.restart_max_rapid = _env_num(
        "ATPU_RESTART_MAX_RAPID", int, res_cfg.restart_max_rapid
    )
    res_cfg.restart_backoff_base_s = _env_num(
        "ATPU_RESTART_BACKOFF_BASE_S", float, res_cfg.restart_backoff_base_s
    )
    res_cfg.restart_backoff_max_s = _env_num(
        "ATPU_RESTART_BACKOFF_MAX_S", float, res_cfg.restart_backoff_max_s
    )
    res_cfg.restart_window_s = _env_num(
        "ATPU_RESTART_WINDOW_S", float, res_cfg.restart_window_s
    )
    res_cfg.store_retries = _env_num("ATPU_STORE_RETRIES", int, res_cfg.store_retries)
    res_cfg.store_retry_base_s = _env_num(
        "ATPU_STORE_RETRY_BASE_S", float, res_cfg.store_retry_base_s
    )
    cfg.features.speculative = bool(
        feats.get("speculative", cfg.features.speculative)
    )
    if "ATPU_SPECULATIVE" in env:
        cfg.features.speculative = env["ATPU_SPECULATIVE"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.paged_kv = bool(feats.get("paged_kv", cfg.features.paged_kv))
    if "ATPU_PAGED_KV" in env:
        cfg.features.paged_kv = env["ATPU_PAGED_KV"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.adaptive_decode = bool(
        feats.get("adaptive_decode", cfg.features.adaptive_decode)
    )
    if "ATPU_ADAPTIVE_DECODE" in env:
        cfg.features.adaptive_decode = env["ATPU_ADAPTIVE_DECODE"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.prefix_cache = bool(
        feats.get("prefix_cache", cfg.features.prefix_cache)
    )
    if "ATPU_PREFIX_CACHE" in env:
        cfg.features.prefix_cache = env["ATPU_PREFIX_CACHE"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.fused_decode = bool(
        feats.get("fused_decode", cfg.features.fused_decode)
    )
    if "ATPU_FUSED_DECODE" in env:
        cfg.features.fused_decode = env["ATPU_FUSED_DECODE"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.inloop_spec = bool(
        feats.get("inloop_spec", cfg.features.inloop_spec)
    )
    if "ATPU_INLOOP_SPEC" in env:
        cfg.features.inloop_spec = env["ATPU_INLOOP_SPEC"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.approx_topk = bool(
        feats.get("approx_topk", cfg.features.approx_topk)
    )
    if "ATPU_APPROX_TOPK" in env:
        cfg.features.approx_topk = env["ATPU_APPROX_TOPK"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.kv_tiering = bool(
        feats.get("kv_tiering", cfg.features.kv_tiering)
    )
    if "ATPU_KV_TIERING" in env:
        cfg.features.kv_tiering = env["ATPU_KV_TIERING"].lower() in (
            "1",
            "true",
            "yes",
        )
    cfg.features.streaming = bool(
        feats.get("streaming", cfg.features.streaming)
    )
    if "ATPU_STREAMING" in env:
        cfg.features.streaming = env["ATPU_STREAMING"].lower() in (
            "1",
            "true",
            "yes",
        )
    try:
        cfg.features.tier_park_linger_s = float(
            feats.get("tier_park_linger_s", cfg.features.tier_park_linger_s)
        )
    except (TypeError, ValueError):
        pass  # malformed linger keeps the default; tiering still works
    return cfg
