"""Core agent data model.

Mirrors the reference ``Agent`` struct and status machine
(reference internal/agent/agent.go:21-78) with TPU-native resource semantics:

- ``image`` (a Docker image ref) becomes ``model``: which engine to run
  (mock echo / JAX LLM) and which model config + checkpoint it serves;
- ``container_id`` becomes ``engine_id``: the runtime handle of the serving
  process placed on TPU chips;
- ``cpu_limit``/``memory_limit`` (NanoCPUs/bytes, agent.go:49-50) become
  ``resources``: number of TPU chips and an HBM budget in bytes — the units
  the slice scheduler actually allocates.

Everything is JSON-serializable; the JSON record stored at ``agent:{id}``
is the durable source of truth that rehydration re-creates engines from
(the analogue of reference Resume re-creating a container purely from the
saved record, agent.go:271-294).
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from enum import Enum
from typing import Any


class AgentStatus(str, Enum):
    """Reference status enum, agent.go:21-29 (created/running/stopped/paused/failed)."""

    CREATED = "created"
    RUNNING = "running"
    STOPPED = "stopped"
    PAUSED = "paused"
    FAILED = "failed"


# Legal transitions enforced by the lifecycle manager. The reference enforces
# these ad hoc (e.g. Stop refuses non-running agents, agent.go:189-191;
# Pause requires running, agent.go:226-231; Resume rehydrates stopped/failed/
# created, agent.go:255-311).
_TRANSITIONS: dict[AgentStatus, set[AgentStatus]] = {
    AgentStatus.CREATED: {AgentStatus.RUNNING, AgentStatus.FAILED},
    AgentStatus.RUNNING: {
        AgentStatus.STOPPED,
        AgentStatus.PAUSED,
        AgentStatus.FAILED,
        AgentStatus.RUNNING,
    },
    AgentStatus.STOPPED: {AgentStatus.RUNNING, AgentStatus.FAILED},
    AgentStatus.PAUSED: {AgentStatus.RUNNING, AgentStatus.STOPPED, AgentStatus.FAILED},
    AgentStatus.FAILED: {AgentStatus.RUNNING, AgentStatus.STOPPED},
}


def can_transition(src: AgentStatus, dst: AgentStatus) -> bool:
    return dst in _TRANSITIONS[src]


@dataclass
class HealthCheckConfig:
    """Reference CheckConfig defaults: /health, 30s interval, 5s timeout,
    3 retries (monitor.go:117-129)."""

    endpoint: str = "/health"
    interval_s: float = 30.0
    timeout_s: float = 5.0
    retries: int = 3

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "HealthCheckConfig | None":
        if d is None:
            return None
        return HealthCheckConfig(
            endpoint=d.get("endpoint", "/health"),
            interval_s=float(d.get("interval_s", 30.0)),
            timeout_s=float(d.get("timeout_s", 5.0)),
            retries=int(d.get("retries", 3)),
        )


@dataclass
class Resources:
    """TPU resource request: chips + HBM budget.

    Replaces the reference's NanoCPU / memory-bytes limits (agent.go:49-50,
    deployment.go:251-337). ``hbm_bytes`` bounds weights+KV for this agent so
    multiple agents can share a slice without eviction storms.
    """

    chips: int = 1
    hbm_bytes: int = 8 * 1024**3

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "Resources":
        if d is None:
            return Resources()
        return Resources(chips=int(d.get("chips", 1)), hbm_bytes=int(d.get("hbm_bytes", 8 * 1024**3)))


@dataclass
class ModelRef:
    """What the agent serves — replaces the Docker image reference.

    ``engine`` selects the serving program ("echo" for the mock-LLM parity
    agent, "llm" for the JAX prefill+decode engine); ``config`` names a model
    config from models/configs.py; ``checkpoint`` optionally points at a
    weight snapshot (absent → randomly initialized, which is what CI uses).
    """

    engine: str = "echo"
    config: str = ""
    checkpoint: str = ""
    options: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any] | str | None) -> "ModelRef":
        if d is None:
            return ModelRef()
        if isinstance(d, str):  # shorthand: "echo" or "llm:llama3-8b"
            engine, _, config = d.partition(":")
            return ModelRef(engine=engine or "echo", config=config)
        return ModelRef(
            engine=d.get("engine", "echo"),
            config=d.get("config", ""),
            checkpoint=d.get("checkpoint", ""),
            options=dict(d.get("options", {})),
        )


@dataclass
class Agent:
    """The durable agent record (reference Agent struct, agent.go:43-59)."""

    id: str
    name: str
    model: ModelRef
    status: AgentStatus = AgentStatus.CREATED
    engine_id: str = ""
    # replica fleet: every engine serving this agent, primary first.
    # ``engine_id`` stays the primary replica's id (replica_ids[0]) so
    # every pre-fleet reader keeps working; single-replica agents may
    # leave this empty (engine_id alone is authoritative then).
    replica_ids: list[str] = field(default_factory=list)
    # engine replicas for this agent: 0 = use the fleet default
    # (config fleet.replicas); >= 1 pins this agent explicitly
    replicas: int = 0
    env: dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    auto_restart: bool = False
    token: str = ""
    health_check: HealthCheckConfig | None = None
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "model": self.model.to_dict(),
            "status": self.status.value,
            "engine_id": self.engine_id,
            "replica_ids": list(self.replica_ids),
            "replicas": self.replicas,
            "env": dict(self.env),
            "resources": self.resources.to_dict(),
            "auto_restart": self.auto_restart,
            "token": self.token,
            "health_check": self.health_check.to_dict() if self.health_check else None,
            "created_at": self.created_at,
            "updated_at": self.updated_at,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Agent":
        return Agent(
            id=d["id"],
            name=d["name"],
            model=ModelRef.from_dict(d.get("model")),
            status=AgentStatus(d.get("status", "created")),
            engine_id=d.get("engine_id", ""),
            replica_ids=list(d.get("replica_ids", []) or []),
            replicas=int(d.get("replicas", 0) or 0),
            env=dict(d.get("env", {})),
            resources=Resources.from_dict(d.get("resources")),
            auto_restart=bool(d.get("auto_restart", False)),
            token=d.get("token", ""),
            health_check=HealthCheckConfig.from_dict(d.get("health_check")),
            created_at=float(d.get("created_at", 0.0)),
            updated_at=float(d.get("updated_at", 0.0)),
        )

    def all_engine_ids(self) -> list[str]:
        """Every engine serving this agent, primary first. Single-replica
        records predate ``replica_ids``, so fall back to ``engine_id``."""
        if self.replica_ids:
            return list(self.replica_ids)
        return [self.engine_id] if self.engine_id else []


def new_agent_id() -> str:
    """ID scheme parity: ``agent-{unix-nanos}`` (reference agent.go:594-596)."""
    return f"agent-{time.time_ns()}"
