"""Wire-protocol constants shared by the proxy, replay worker, and engine
serve layer. One definition site: these names ARE the contract between the
control plane and engines — a rename that only lands on one side silently
breaks dispatch classification or header handling.
"""

from __future__ import annotations

# proxy ↔ engine headers
REPLAY_HEADER = "X-Agentainer-Replay"
REQUEST_ID_HEADER = "X-Agentainer-Request-ID"
# end-to-end deadline: remaining milliseconds the caller will wait; the
# proxy journals the absolute instant and forwards the remaining budget
DEADLINE_HEADER = "X-Agentainer-Deadline-Ms"
# engine process is up but its model is still loading
LOADING_HEADER = "X-Agentainer-Loading"
# engine SIGTERM drain in progress (treated like loading: entry stays
# pending, replays on respawn)
DRAINING_HEADER = "X-Agentainer-Draining"
# the engine dropped the request by deadline/cancel policy — dead-letter,
# never archive the notice as the request's completed response
EXPIRED_HEADER = "X-Agentainer-Expired"
# the request itself broke prefill on a HEALTHY engine (deterministic
# input fault, not a crash): the proxy charges poison accounting instead
# of archiving the 500 — two strikes dead-letters it (journal.mark_failed
# poison=True)
PREFILL_POISON_HEADER = "X-Agentainer-Prefill-Poisoned"

# SSE streaming (stream=true on /chat, features.streaming)
STREAM_CONTENT_TYPE = "text/event-stream"
# standard SSE reconnect header; doubles as the proxy→engine splice
# cursor on mid-stream failover: the engine serve layer re-emits the
# deterministic sequence and skips every offset <= this value
LAST_EVENT_ID_HEADER = "Last-Event-ID"
# SSE event names on the wire
STREAM_EVENT_TOKEN = "token"
STREAM_EVENT_DONE = "done"
STREAM_EVENT_ERROR = "error"

# dispatch_to_agent sentinel outcomes (never valid HTTP statuses)
DISPATCH_ENGINE_GONE = -1  # connection refused / engine vanished → stays pending
DISPATCH_FAILED = -2  # timeout or protocol error → retry accounted
DISPATCH_EXPIRED = -3  # deadline passed → dead-lettered, no retry charged
DISPATCH_IN_FLIGHT = -4  # lost the processing CAS → another dispatcher owns it
