"""Resilience primitives: circuit breaker + bounded jittered backoff.

Shared by the store client (engine side) and the control plane (proxy
side). Kept dependency-free — core/ must import nothing above it.
"""

from __future__ import annotations

import random
import threading
import time


class CircuitBreaker:
    """Failure-counting breaker for a dependency that can hang or flap.

    Closed → every call allowed. ``failure_threshold`` consecutive
    failures open it: calls are refused instantly (the caller answers
    503 + Retry-After instead of stacking timeouts on a dead store).
    After ``cooldown_s`` ONE probe call is allowed through (half-open);
    its outcome closes the breaker or re-opens it for another cooldown.

    Thread-safe; success/failure recording is the caller's job because
    only the caller knows which exceptions are the dependency's fault.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 2.0):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False
        # lifetime counters for the metrics plane
        self.opens_total = 0
        self.refused_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """May a call proceed right now? In half-open state exactly one
        caller wins the probe; the rest stay refused until it settles."""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.cooldown_s:
                self.refused_total += 1
                return False
            if self._probing:
                self.refused_total += 1
                return False
            self._probing = True
            return True

    def ok(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def fail(self) -> None:
        with self._lock:
            self._probing = False
            self._failures += 1
            if self._opened_at is not None:
                # failed probe: full cooldown again
                self._opened_at = time.monotonic()
            elif self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self.opens_total += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": (
                    "closed"
                    if self._opened_at is None
                    else (
                        "half-open"
                        if time.monotonic() - self._opened_at >= self.cooldown_s
                        else "open"
                    )
                ),
                "consecutive_failures": self._failures,
                "opens_total": self.opens_total,
                "refused_total": self.refused_total,
            }


class KeyedBreakers:
    """A family of independent CircuitBreakers keyed by string (one per
    engine replica): a replica that keeps failing opens ITS breaker only,
    so the agent's other replicas keep serving — the whole point of the
    per-replica split versus one breaker per agent."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 2.0):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = CircuitBreaker(
                    failure_threshold=self.failure_threshold,
                    cooldown_s=self.cooldown_s,
                )
            return br

    def drop(self, key: str) -> None:
        """Forget a replaced/removed replica's breaker (a respawned engine
        gets a fresh id, so stale entries would only leak)."""
        with self._lock:
            self._breakers.pop(key, None)

    def stats(self) -> dict[str, dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {key: br.stats() for key, br in items}


def retry_after_jitter(
    base_s: float, rng: random.Random | None = None, spread: float = 0.5
) -> int:
    """Retry-After seconds with multiplicative jitter in [1-spread/2,
    1+spread/2): a fleet of clients shed in the same instant must NOT come
    back in the same instant — synchronized retries re-stampede exactly
    the replica that was recovering. Pass a seeded ``rng`` for a
    deterministic sequence (tests, chaos). Result is a whole second >= 1
    (the HTTP header is integer seconds)."""
    r = rng or random
    return max(1, int(round(base_s * (1.0 - spread / 2 + spread * r.random()))))


def backoff_delays(
    retries: int,
    base_s: float = 0.05,
    max_s: float = 2.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
) -> list[float]:
    """Exponential backoff schedule with multiplicative jitter: attempt n
    sleeps ``base * 2**n`` (capped) scaled by ``1 ± jitter/2``. Pass a
    seeded ``rng`` for a deterministic schedule (chaos soak)."""
    r = rng or random
    out = []
    for n in range(max(0, int(retries))):
        d = min(max_s, base_s * (2**n))
        out.append(d * (1.0 - jitter / 2 + jitter * r.random()))
    return out
