"""Framework error taxonomy.

The reference returns ``fmt.Errorf`` strings surfaced as HTTP 4xx/5xx by the
API layer (e.g. "agent not found" → 404, server.go:236-241). Typed exceptions
here map to status codes in server/app.py.
"""


class AgentainerError(Exception):
    http_status = 500


class AgentNotFound(AgentainerError):
    http_status = 404

    def __init__(self, agent_id: str):
        super().__init__(f"agent not found: {agent_id}")
        self.agent_id = agent_id


class InvalidInput(AgentainerError):
    http_status = 400


class InvalidTransition(AgentainerError):
    http_status = 409

    def __init__(self, agent_id: str, src: str, op: str):
        super().__init__(f"agent {agent_id} is {src}; cannot {op}")


class ResourceExhausted(AgentainerError):
    """Slice scheduler cannot place the agent (not enough chips / HBM)."""

    http_status = 409


class BackendError(AgentainerError):
    http_status = 502


class Unauthorized(AgentainerError):
    http_status = 401
