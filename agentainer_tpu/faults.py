"""Deterministic fault-injection plane (failpoints).

A registry of NAMED failpoints wired at the critical seams across every
layer — store ops, store-client RPC, journal transitions, replay/proxy
dispatch, health probes, engine submit/prefill/decode/snapshot, watcher
respawn. Each failpoint is armed with an error type, an injected delay,
a *seeded* probability, and a fire budget, so a chaos schedule replays
bit-identically run to run (scripts/chaos_soak.py drives exactly that).

Design constraints, in order:

1. **Zero overhead when disarmed.** ``fire()`` at a hot seam (the decode
   worker loop ticks it) is one function call + one empty-dict truthiness
   check when nothing is armed. No locks, no lookups, no allocation.
2. **Deterministic.** Probabilistic failpoints draw from a per-failpoint
   ``random.Random(seed)`` — the decision SEQUENCE is a pure function of
   (seed, evaluation order). Fire counts bound total injections exactly.
3. **Explicit arming only.** Nothing fires unless an operator armed it via
   config (``resilience.faults``), env (``ATPU_FAULTS=...``), the authed
   API (``POST /internal/faults``), or a test calling :func:`arm`. The
   default state of this module is a no-op pass-through — the A/B guard
   is the entire existing test suite running with the registry empty.

Arming grammar (env/config/CLI/API all share it)::

    name[:key=value[,key=value...]][;name2...]

    ATPU_FAULTS="store.get:error=ConnectionError,probability=0.3,seed=7;\
engine.prefill:error=RuntimeError,count=2;proxy.dispatch:delay_ms=500,error=none"

Keys: ``error`` (exception class name from :data:`ERROR_TYPES`, or
``none`` for delay-only), ``delay_ms``, ``probability`` (0..1, seeded),
``count`` (max fires; -1 unlimited), ``seed``. A bare ``name`` raises
:class:`FaultInjected` on every evaluation.

The failpoint catalog (names and where they cut) is documented in
docs/RESILIENCE.md §"Fault injection".
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field


class FaultInjected(RuntimeError):
    """Default injected error: unmistakably synthetic in logs/metrics."""


# Exception classes a failpoint may raise. Restricted on purpose: these are
# the transport/runtime shapes the planes under test actually classify
# (ConnectionError → crash heuristic, TimeoutError → retry accounting, ...).
ERROR_TYPES: dict[str, type[BaseException]] = {
    "FaultInjected": FaultInjected,
    "ConnectionError": ConnectionError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
    "IOError": OSError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
}


@dataclass
class Failpoint:
    name: str
    error: str = "FaultInjected"  # "none" → delay-only
    delay_ms: float = 0.0
    probability: float = 1.0
    count: int = -1  # remaining fires; -1 = unlimited; 0 = exhausted (inert)
    seed: int = 0
    fired: int = 0
    evaluated: int = 0
    _rng: random.Random = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self) -> None:
        if self.error != "none" and self.error not in ERROR_TYPES:
            raise ValueError(
                f"unknown failpoint error type {self.error!r}; "
                f"known: {sorted(ERROR_TYPES)} or 'none'"
            )
        self.probability = min(1.0, max(0.0, float(self.probability)))
        self._rng = random.Random(self.seed)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "error": self.error,
            "delay_ms": self.delay_ms,
            "probability": self.probability,
            "count": self.count,
            "seed": self.seed,
            "fired": self.fired,
            "evaluated": self.evaluated,
        }


# The failpoint registry-as-code: every fire()/fire_async() seam in the
# tree, one name per cut. The static analyzer (ATP004) keeps this
# three-way consistent with the actual call sites and with the
# RESILIENCE.md catalog table, so a seam can't silently drop out of the
# chaos schedule. arm() intentionally does NOT enforce membership —
# tests arm synthetic names — but anything wired into product code
# must be listed here.
CATALOG: frozenset[str] = frozenset(
    {
        "store.get",
        "store.set",
        "store.cas",
        "store.aof_flush",
        "store_client.rpc",
        "journal.mark_processing",
        "journal.complete",
        "replay.dispatch",
        "proxy.dispatch",
        "health.probe",
        "engine.submit",
        "engine.prefill",
        "engine.decode_step",
        "engine.fused_decode",
        # SSE streaming seams: the engine serve layer's per-event token
        # write (firing = the upstream stream dies mid-emission → the
        # proxy's failover splice takes over) and the proxy's per-event
        # forward to the client (firing = a proxy-side dispatch failure
        # mid-stream — the journal cursor keeps the splice exact)
        "engine.stream",
        "proxy.stream_emit",
        "engine.snapshot",
        "engine.page_alloc",
        # tiered KV hierarchy: a firing kv_demote leaves the session
        # device-resident (parking is an optimization); a firing kv_promote
        # keeps the session parked and the triggering turn 429s typed —
        # context is preserved and a retry recovers
        "engine.kv_demote",
        "engine.kv_promote",
        "watcher.respawn",
        # fleet seams: the routing tier's replica choice (firing = a stale
        # routing table hands back a dead replica), the replica heartbeat
        # lease refresh (firing = a healthy replica's lease lapses → SUSPECT
        # flapping), and the session-affinity handoff off a dead replica
        # (firing = the session stays pinned to the corpse one more dispatch)
        "router.pick",
        "replica.lease",
        "replica.handoff",
    }
)


# The fast-path guard: fire() checks THIS dict's truthiness and returns.
# Mutations happen under _lock; the read path relies on the GIL-atomic
# dict read (a stale read during arm/disarm is acceptable by design).
_REGISTRY: dict[str, Failpoint] = {}
_lock = threading.Lock()


def arm(
    name: str,
    error: str = "FaultInjected",
    delay_ms: float = 0.0,
    probability: float = 1.0,
    count: int = -1,
    seed: int = 0,
) -> Failpoint:
    """Arm (or re-arm, resetting counters/RNG) one failpoint."""
    fp = Failpoint(
        name=name,
        error=error,
        delay_ms=float(delay_ms),
        probability=float(probability),
        count=int(count),
        seed=int(seed),
    )
    with _lock:
        _REGISTRY[name] = fp
    return fp


def disarm(name: str) -> bool:
    with _lock:
        return _REGISTRY.pop(name, None) is not None


def disarm_all() -> None:
    with _lock:
        _REGISTRY.clear()


def armed(name: str) -> bool:
    return name in _REGISTRY


def active() -> list[dict]:
    """Specs + live counters of every armed failpoint (API/CLI surface)."""
    with _lock:
        return [fp.to_dict() for fp in _REGISTRY.values()]


def _decide(name: str) -> tuple[float, BaseException | None] | None:
    """Evaluate one failpoint; returns (delay_s, error | None) when it
    fires, None when it doesn't. Mutates counters under the lock so two
    racing seams cannot both spend the same fire-count budget."""
    with _lock:
        fp = _REGISTRY.get(name)
        if fp is None:
            return None
        fp.evaluated += 1
        if fp.count == 0:
            return None  # budget spent: inert but still listed in active()
        if fp.probability < 1.0 and fp._rng.random() >= fp.probability:
            return None
        if fp.count > 0:
            fp.count -= 1
        fp.fired += 1
        delay_s = fp.delay_ms / 1000.0
        err: BaseException | None = None
        if fp.error != "none":
            err = ERROR_TYPES[fp.error](f"failpoint {name!r} injected {fp.error}")
    return delay_s, err


def fire(name: str) -> None:
    """Synchronous seam: sleep the injected delay, raise the injected
    error. The disarmed cost is one empty-dict check. Note a ``delay_ms``
    on a sync seam stalls the CALLING THREAD — for store/journal seams
    invoked from the daemon loop that is the whole event loop, which is a
    faithful model of a synchronously-hanging store; async seams use
    :func:`fire_async` so only the injected op slows down."""
    if not _REGISTRY:
        return
    hit = _decide(name)
    if hit is None:
        return
    delay_s, err = hit
    if delay_s > 0:
        time.sleep(delay_s)
    if err is not None:
        raise err


async def fire_async(name: str) -> None:
    """Async seam: identical semantics, but the delay yields the event
    loop (a failpoint must not freeze co-tenant traffic to delay one op)."""
    if not _REGISTRY:
        return
    hit = _decide(name)
    if hit is None:
        return
    delay_s, err = hit
    if delay_s > 0:
        import asyncio

        await asyncio.sleep(delay_s)
    if err is not None:
        raise err


# -- arming grammar --------------------------------------------------------
_FLOAT_KEYS = {"delay_ms", "probability"}
_INT_KEYS = {"count", "seed"}


def parse_spec(spec: str) -> list[dict]:
    """Parse the shared grammar into arm() kwargs (no side effects)."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, opts = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"failpoint spec {part!r} has no name")
        kw: dict = {"name": name}
        for item in filter(None, (s.strip() for s in opts.split(","))):
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"failpoint option {item!r} is not key=value")
            if key in _FLOAT_KEYS:
                kw[key] = float(val)
            elif key in _INT_KEYS:
                kw[key] = int(val)
            elif key == "error":
                kw[key] = val.strip()
            else:
                raise ValueError(
                    f"unknown failpoint option {key!r}; known: error, "
                    "delay_ms, probability, count, seed"
                )
        out.append(kw)
    return out


def arm_spec(spec: str) -> list[str]:
    """Arm every failpoint in a grammar string; returns the armed names."""
    names = []
    for kw in parse_spec(spec):
        arm(**kw)
        names.append(kw["name"])
    return names


def arm_from_env(env_var: str = "ATPU_FAULTS") -> list[str]:
    """Arm from the environment (engine subprocesses inherit the daemon's
    env, so a daemon-armed ``engine.*`` failpoint reaches every engine it
    spawns). No-op when unset."""
    import os

    spec = os.environ.get(env_var, "")
    return arm_spec(spec) if spec else []
