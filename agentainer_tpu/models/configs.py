"""Model config registry.

The flagship targets are Llama-3-8B (BASELINE.json config #2) and
Mixtral-8x7B expert-parallel (config #5). Tiny variants exist for CI and the
virtual CPU mesh — same code path, small shapes.

All dims are chosen TPU-aware: head_dim and hidden sizes are multiples of
128 (MXU/VPU lane width) for the real configs.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    # MoE (0 experts → dense FFN)
    n_experts: int = 0
    experts_per_token: int = 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    def param_count(self) -> int:
        """Exact parameter count of models/llama.init_params' pytree."""
        embed = self.vocab_size * self.dim
        per_layer_attn = self.dim * self.dim + 2 * self.dim * (
            self.n_kv_heads * self.head_dim
        ) + self.dim * self.dim
        ffn = 3 * self.dim * self.ffn_dim
        if self.is_moe:
            ffn = self.n_experts * ffn + self.dim * self.n_experts
        per_layer = per_layer_attn + ffn + 2 * self.dim
        return 2 * embed + self.n_layers * per_layer + self.dim

    def param_bytes(self, dtype_bytes: int = 2) -> int:
        """Rough weight footprint for the HBM planner (bf16 default)."""
        return dtype_bytes * self.param_count()

    def active_param_count(self) -> int:
        """Params a single token's forward actually touches: for MoE only
        ``experts_per_token`` of the expert FFNs contract with each token
        (the engine's dense-einsum MoE still computes all experts on one
        chip, but FLOP-utilization accounting follows the routed math)."""
        if not self.is_moe:
            return self.param_count()
        full_ffn = 3 * self.dim * self.ffn_dim
        unused = (self.n_experts - self.experts_per_token) * full_ffn
        return self.param_count() - self.n_layers * unused

    def flops_per_token(self, context_len: int) -> float:
        """Forward-pass FLOPs to process ONE token with ``context_len``
        tokens of attendable KV (matmul FLOPs = 2 × MACs; norms/rope/softmax
        are O(d) noise and excluded). This is the per-step FLOP model MFU is
        computed from (VERDICT r2 item 2): decode steps pass the current
        sequence position, prefill passes the mean position of the chunk.
        """
        # every weight matmul: 2 FLOPs per weight actually contracted
        matmul = 2.0 * self.active_param_count()
        # attention scores + value combine: q·K^T and p·V, each
        # 2 * heads * head_dim * context MACs → 4 FLOPs per context slot
        attn = 4.0 * self.n_heads * self.head_dim * context_len
        return matmul + self.n_layers * attn


_REGISTRY: dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


# Llama-3-8B architecture (public numbers: 32 layers, 4096 dim, 32 heads /
# 8 KV heads (GQA), 14336 FFN, 128256 vocab, rope theta 5e5).
LLAMA3_8B = register(
    ModelConfig(
        name="llama3-8b",
        vocab_size=128_256,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14_336,
        max_seq_len=8192,
        rope_theta=500_000.0,
    )
)

# Mixtral-8x7B architecture (32 layers, 4096 dim, 32/8 heads, 14336 FFN,
# 8 experts top-2, 32000 vocab, theta 1e6).
MIXTRAL_8X7B = register(
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32_000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14_336,
        max_seq_len=32_768,
        rope_theta=1_000_000.0,
        n_experts=8,
        experts_per_token=2,
    )
)

# Tiny CI configs — same code paths, CPU-mesh friendly shapes.
TINY = register(
    ModelConfig(
        name="tiny",
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=256,
        rope_theta=10_000.0,
    )
)

TINY_MOE = register(
    ModelConfig(
        name="tiny-moe",
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=256,
        rope_theta=10_000.0,
        n_experts=4,
        experts_per_token=2,
    )
)

# A mid-size single-chip benchmark config: large enough to exercise the MXU,
# small enough to init with random weights quickly on one v5e chip.
BENCH_1B = register(
    ModelConfig(
        name="bench-1b",
        vocab_size=32_000,
        dim=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        ffn_dim=5632,
        max_seq_len=4096,
        rope_theta=500_000.0,
    )
)


def get_config(name: str) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model config {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_configs() -> list[str]:
    return sorted(_REGISTRY)
