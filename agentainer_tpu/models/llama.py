"""Llama-3-family transformer — functional JAX, TPU-first.

Green-field (the reference proxies to external LLM APIs and has no model
code — SURVEY.md §2.3); this is the in-process engine's model, designed for
XLA from the start:

- **pytree params with stacked layers**: every per-layer weight carries a
  leading ``[n_layers, ...]`` axis and the forward pass is one
  ``lax.scan`` over layers — one traced block regardless of depth (fast
  compiles, and the natural substrate for pipeline parallelism later);
- **static shapes everywhere**: the KV cache is a fixed ``[L, B, S, KV, hd]``
  arena written by scatter at per-sequence positions, so the same compiled
  function serves prefill and continuous-batching decode (ragged batches);
- **bf16 weights/activations, f32 softmax/norms** — MXU-friendly;
- GQA grouping instead of repeated K/V (HBM bandwidth);
- sharding-agnostic: parallel/sharding.py maps these pytree paths to mesh
  axes; nothing here names a device.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (
    attention_reference,
    cache_attention,
    causal_mask,
    flash_attention,
    paged_cache_attention,
    scatter_paged_kv,
)
from ..ops.norms import rms_norm
from ..ops.quant import dequant, embed_lookup
from ..ops.rope import apply_rope
from .configs import ModelConfig


class KVCache(NamedTuple):
    """Static-shape KV arena: k/v ``[L, B, S, KV, hd]``."""

    k: jnp.ndarray
    v: jnp.ndarray

    @staticmethod
    def create(
        cfg: ModelConfig, batch: int, max_seq: int, dtype: jnp.dtype = jnp.bfloat16
    ) -> "KVCache":
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


class PagedKVCache(NamedTuple):
    """Block-table KV arena: a global pool of fixed-size pages
    ``[L, n_pages, page_size, KV, hd]``. A sequence owns a LIST of pages
    (its block table row) instead of a dense arena row, so resident
    sessions are bounded by the pool, not the compiled batch width, and
    shared prefixes are refcounted page mappings instead of copies. Same
    pytree shape discipline as :class:`KVCache` (two leaves, leading layer
    axis) so the engine's scan/donation/sharding machinery applies
    unchanged — under tp the KV-head axis (3) shards exactly like the
    dense arena's."""

    k: jnp.ndarray
    v: jnp.ndarray

    @staticmethod
    def create(
        cfg: ModelConfig,
        n_pages: int,
        page_size: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
        return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def init_params(cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16) -> dict:
    """Random init (truncated-normal-ish 0.02 scale). Checkpoint loading maps
    onto the same pytree (engine/checkpoint.py)."""
    keys = iter(jax.random.split(key, 16))
    d, hd = cfg.dim, cfg.head_dim

    def w(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((cfg.n_layers, d), dtype),
        "wq": w(next(keys), cfg.n_layers, d, cfg.n_heads * hd),
        "wk": w(next(keys), cfg.n_layers, d, cfg.n_kv_heads * hd),
        "wv": w(next(keys), cfg.n_layers, d, cfg.n_kv_heads * hd),
        "wo": w(next(keys), cfg.n_layers, cfg.n_heads * hd, d),
        "mlp_norm": jnp.ones((cfg.n_layers, d), dtype),
    }
    if cfg.is_moe:
        layers.update(
            {
                "router": w(next(keys), cfg.n_layers, d, cfg.n_experts),
                "w_gate": w(next(keys), cfg.n_layers, cfg.n_experts, d, cfg.ffn_dim),
                "w_up": w(next(keys), cfg.n_layers, cfg.n_experts, d, cfg.ffn_dim),
                "w_down": w(next(keys), cfg.n_layers, cfg.n_experts, cfg.ffn_dim, d),
            }
        )
    else:
        layers.update(
            {
                "w_gate": w(next(keys), cfg.n_layers, d, cfg.ffn_dim),
                "w_up": w(next(keys), cfg.n_layers, d, cfg.ffn_dim),
                "w_down": w(next(keys), cfg.n_layers, cfg.ffn_dim, d),
            }
        )
    return {
        "embed": w(next(keys), cfg.vocab_size, d),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
        "lm_head": w(next(keys), d, cfg.vocab_size),
    }


def _mlp(x: jnp.ndarray, lp: dict) -> jnp.ndarray:
    """SwiGLU."""
    gate = jax.nn.silu(x @ lp["w_gate"])
    return (gate * (x @ lp["w_up"])) @ lp["w_down"]


def _moe_mlp(x: jnp.ndarray, lp: dict, cfg: ModelConfig) -> jnp.ndarray:
    """Dense-einsum MoE (top-k routing, all experts computed, masked combine).

    Simple and branch-free, but ~E/k× the routed FLOPs — the single-chip
    fallback. The routed path (`_moe_mlp_routed`, and parallel/expert.py
    under a mesh) computes only dispatched tokens and is the serving
    default wherever ep > 1.
    """
    b, t, d = x.shape
    logits = x @ lp["router"]  # [B,T,E]
    weights, chosen = lax.top_k(logits, cfg.experts_per_token)
    weights = jax.nn.softmax(weights.astype(jnp.float32), axis=-1).astype(x.dtype)
    onehot = jax.nn.one_hot(chosen, cfg.n_experts, dtype=x.dtype)  # [B,T,K,E]
    combine = jnp.einsum("btk,btke->bte", weights, onehot)  # [B,T,E]
    gate = jax.nn.silu(jnp.einsum("btd,edf->btef", x, lp["w_gate"]))
    up = jnp.einsum("btd,edf->btef", x, lp["w_up"])
    expert_out = jnp.einsum("btef,efd->bted", gate * up, lp["w_down"])
    return jnp.einsum("bted,bte->btd", expert_out, combine)


# token counts at or below this run routed MoE with cap = n (dropless) even
# for prefill-shaped (t > 1) calls, where dropless is free anyway. Decode
# calls (t == 1) are ALWAYS dropless via the shape gate in _moe_mlp_routed,
# whatever max_batch is.
_DROPLESS_MAX_N = 64


def routed_capacity(n_tokens: int, n_experts: int, k: int, capacity_factor: float) -> int:
    """Static per-expert dispatch-buffer size: ``capacity_factor`` × the
    perfectly-balanced share (n·k/E), clamped to n — top-k indices are
    distinct, so a token contributes at most ONE slot per expert and C = n
    is dropless no matter how skewed the router. Callers force
    droplessness with a large factor."""
    import math

    return max(1, min(n_tokens, math.ceil(n_tokens * k / n_experts * capacity_factor)))


def _moe_mlp_routed(
    x: jnp.ndarray,
    lp: dict,
    cfg: ModelConfig,
    *,
    capacity_factor: float = 2.0,
    base: int = 0,
) -> jnp.ndarray:
    """Top-k token-dispatch MoE — GShard-style one-hot dispatch/combine
    einsums (static shapes, MXU matmuls, no gather/scatter).

    Computes ONLY routed (token, expert) work: per-token MLP FLOPs are
    ∝ k·capacity_factor, not E — the dense ``_moe_mlp`` computes every
    expert for every token and masks at combine, ~E/k× wasted FLOPs
    (VERDICT r3 missing #5). A token overflowing an expert's capacity
    loses that expert's contribution (GShard drop semantics); capacity
    clamps at N so droplessness is one large factor away.

    ``base`` supports the EP shard_map wrapper (parallel/expert.py): the
    router is replicated so routing runs over the FULL expert set on every
    device, while ``lp`` carries only the E/ep local experts starting at
    ``base`` — out-of-range choices one-hot to zero rows, and a psum over
    ep combines the per-device partial outputs.
    """
    b, t, d = x.shape
    w_gate = lp["w_gate"]
    e_loc = w_gate.shape[0]
    n, k = b * t, cfg.experts_per_token
    # Decode-sized calls (t==1, n = max_batch) go DROPLESS: the engine's
    # pipelined decode feeds every lane — including parked/idle ones —
    # through this path, and cumsum slot assignment would let a parked
    # lane's garbage token steal a real token's expert capacity (ADVICE
    # r4). Gate on the CALL SHAPE, not a fixed token count: the old
    # n <= _DROPLESS_MAX_N gate silently reverted engines configured with
    # max_batch > 64 to cf-capped routing — exactly the stealing bug again
    # (ADVICE r5). cap = n makes stealing impossible and costs almost
    # nothing at decode batch sizes; prefill (t = bucket, all real tokens
    # from ONE sequence) keeps the cf-bounded buffers unless it is small
    # enough that dropless is free anyway.
    if t == 1 or n <= _DROPLESS_MAX_N:
        cap = n
    else:
        cap = routed_capacity(n, cfg.n_experts, k, capacity_factor)
    xf = x.reshape(n, d)
    logits = xf @ lp["router"]  # [N, E] — full expert set
    weights, chosen = lax.top_k(logits, k)
    weights = jax.nn.softmax(weights.astype(jnp.float32), axis=-1).astype(x.dtype)
    # one-hot over LOCAL experts; choices outside [base, base+e_loc) fall
    # out of range and one-hot to all-zero rows
    local = (chosen - base).reshape(n * k)
    oh = jax.nn.one_hot(local, e_loc, dtype=jnp.float32)  # [S, E_loc]
    # each assignment's slot in its expert's buffer = how many earlier
    # assignments picked that expert (f32 cumsum is exact well past any
    # realistic S); slots ≥ cap one-hot to zero → the token drops
    slot = ((jnp.cumsum(oh, axis=0) - 1.0) * oh).astype(jnp.int32)
    disp = oh[:, :, None] * jax.nn.one_hot(slot, cap, dtype=jnp.float32)
    disp = disp.reshape(n, k, e_loc, cap)
    # a token's k choices are distinct experts, so summing over k leaves at
    # most one nonzero per (token, expert) — dispatch/combine stay one-hot
    disp_tok = disp.sum(1).astype(x.dtype)  # [N, E_loc, C]
    combine_tok = (disp * weights[..., None, None]).sum(1).astype(x.dtype)
    xe = jnp.einsum("nd,nec->ecd", xf, disp_tok)  # gather into [E_loc, C, D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, w_gate))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"])
    out_buf = jnp.einsum("ecf,efd->ecd", gate * up, lp["w_down"])
    out = jnp.einsum("ecd,nec->nd", out_buf, combine_tok)  # weighted scatter
    return out.reshape(b, t, d)


def _attention_block(
    x: jnp.ndarray,
    lp: dict,
    cfg: ModelConfig,
    positions: jnp.ndarray,
    mask: jnp.ndarray,
    ck: jnp.ndarray | None,
    cv: jnp.ndarray | None,
    use_flash: bool,
    attn_impl=None,
    cache_attn_impl=None,
    block_table=None,
):
    b, t, d = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(b, t, cfg.n_heads, cfg.head_dim)
    k = (h @ lp["wk"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)

    if ck is not None and block_table is not None:
        # paged arena: write through the block table into pool pages, then
        # attend over the gathered page view — same masking rule, same
        # numbers as the dense scatter+attend below (bit-exact parity)
        ck, cv = scatter_paged_kv(ck, cv, k, v, block_table, positions)
        attn = paged_cache_attention(
            q, ck, cv, block_table, positions, use_pallas=use_flash
        )
    elif ck is not None:
        # scatter this step's K/V into the arena at per-sequence positions
        batch_idx = jnp.arange(b)[:, None]
        ck = ck.at[batch_idx, positions].set(k)
        cv = cv.at[batch_idx, positions].set(v)
        if cache_attn_impl is not None:
            # meshed engines: per-device Pallas flash via shard_map
            # (parallel/flash_mesh.py) — GSPMD can't partition pallas_call
            attn = cache_attn_impl(q, ck, cv, positions)
        else:
            attn = cache_attention(q, ck, cv, positions, use_pallas=use_flash)
    elif attn_impl is not None:
        # caller-supplied causal self-attention: the sequence-parallel
        # training path passes ring/Ulysses attention here (q/k/v are
        # sequence shards; global positions came in via ``positions``)
        attn = attn_impl(q, k, v)
    elif use_flash:
        attn = flash_attention(q, k, v, causal=True)
    else:
        attn = attention_reference(q, k, v, mask=mask)
    out = attn.reshape(b, t, cfg.n_heads * cfg.head_dim) @ lp["wo"]
    return x + out, ck, cv


def forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32
    cache: KVCache | None = None,
    use_flash: bool = True,
    attn_impl=None,
    cache_attn_impl=None,
    moe_impl=None,
    block_table: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache | None]:
    """Returns (logits [B, T, V], updated cache).

    With a cache: serves prefill (T = prompt chunk) and decode (T = 1) with
    per-sequence positions — the continuous-batching engine relies on this.
    With ``block_table`` the cache is a :class:`PagedKVCache` pool and
    every KV read/write goes through the table (paged serving); the cache
    returned is the updated pool.
    Without: pure causal self-attention (training / eval); ``attn_impl``
    overrides the attention for sequence-parallel runs (ring / Ulysses).
    ``moe_impl`` overrides the MoE MLP (routed token-dispatch, meshed EP).
    """
    x = embed_lookup(params["embed"], tokens)
    if cache is not None:
        mask = None  # cache_attention masks from positions (in-kernel on TPU)
    else:
        t = tokens.shape[1]
        mask = jnp.broadcast_to(causal_mask(t), (tokens.shape[0], t, t))

    lp_stack = params["layers"]

    def layer_step(carry, inputs):
        x = carry
        if cache is not None:
            lp, ck, cv = inputs
        else:
            lp = inputs
        # int8-quantized weights (engine/quant.py) dequantize per layer
        # slice here: HBM holds the int8 stack, only the current layer is
        # dense, and XLA fuses the convert into the consuming matmuls
        lp = {k: dequant(v) for k, v in lp.items()}
        if cache is not None:
            x, ck, cv = _attention_block(
                x, lp, cfg, positions, mask, ck, cv, use_flash,
                cache_attn_impl=cache_attn_impl,
                block_table=block_table,
            )
        else:
            x, _, _ = _attention_block(
                x, lp, cfg, positions, mask, None, None, use_flash, attn_impl
            )
            ck = cv = jnp.zeros((0,), x.dtype)  # scan needs a leaf
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.is_moe:
            x = x + (moe_impl(h, lp) if moe_impl is not None else _moe_mlp(h, lp, cfg))
        else:
            x = x + _mlp(h, lp)
        return x, (ck, cv)

    if cache is not None:
        x, (new_k, new_v) = lax.scan(layer_step, x, (lp_stack, cache.k, cache.v))
        new_cache = type(cache)(new_k, new_v)
    else:
        x, _ = lax.scan(layer_step, x, lp_stack)
        new_cache = None

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ dequant(params["lm_head"])).astype(jnp.float32)
    return logits, new_cache


def greedy_decode(
    params: dict,
    cfg: ModelConfig,
    prompt: jnp.ndarray,  # [B, Tp]
    max_new_tokens: int,
    cache_len: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Reference generation loop: prefill then a ``lax.scan`` decode.
    Engine-grade batching lives in engine/llm.py; this is the simple path
    used by tests and the graft entry."""
    b, tp = prompt.shape
    cache = KVCache.create(cfg, b, cache_len, dtype=dtype)
    positions = jnp.broadcast_to(jnp.arange(tp), (b, tp))
    logits, cache = forward(params, cfg, prompt, positions, cache)
    last = jnp.argmax(logits[:, -1], axis=-1)  # [B]

    def step(carry, i):
        cache, tok, pos = carry
        logits, cache = forward(
            params, cfg, tok[:, None], pos[:, None], cache
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        return (cache, nxt, pos + 1), nxt

    (_, _, _), toks = lax.scan(
        step, (cache, last, jnp.full((b,), tp)), jnp.arange(max_new_tokens - 1)
    )
    return jnp.concatenate([last[:, None], toks.T], axis=1)  # [B, max_new_tokens]
