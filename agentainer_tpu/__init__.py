"""agentainer_tpu — a TPU-native runtime for self-hosted LLM agents.

A brand-new framework with the capabilities of Agentainer-lab (reference:
/root/reference, a Go control plane that runs agents as Docker containers and
proxies HTTP to them), re-designed TPU-first:

- agents are model-serving programs placed on TPU chips by a slice scheduler
  (replacing the Docker-socket backend, reference pkg/docker + internal/agent),
- the inference path is an in-process JAX/XLA prefill+decode engine with
  continuous batching (replacing the external OpenAI/Gemini HTTP calls of
  reference examples/*-agent),
- the durable request journal drains into the batching scheduler
  (reference internal/requests journaled into Redis and re-POSTed via proxy),
- crash recovery restores conversation + KV-cache state from the store
  (reference restores only container infra state, docs/RESILIENT_AGENTS.md),
- models shard over an ICI device mesh via jax.sharding / shard_map
  (TP / DP / SP-ring-attention / EP), not NCCL.
"""

__version__ = "0.1.0"
