"""Sharded training step — next-token LM loss over a (dp, tp, sp, ep) mesh.

The reference has no training; agents there are frozen external APIs. Here
agents are models the framework owns, so fine-tuning them in place is a
framework feature — and this module is also the multi-chip contract the
driver dry-runs (``__graft_entry__.dryrun_multichip``): params sharded per
parallel/sharding.py, batch sharded over dp×sp, optimizer state sharded like
the params, one jit containing forward, loss, backward, and the optax update
— XLA/GSPMD inserts the gradient all-reduces over ICI.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.configs import ModelConfig
from .models.llama import forward, init_params
from .parallel.sharding import batch_spec, param_shardings


class TrainState(NamedTuple):
    params: dict
    opt_state: Any
    step: jnp.ndarray


def loss_fn(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    attn_impl=None,
    input_sharding=None,
) -> jnp.ndarray:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1].

    ``input_sharding`` re-shards the sliced inputs (sequence-parallel runs:
    raw tokens arrive dp-sharded because T+1 doesn't divide by sp; the T-long
    inputs do, and annotating them here makes ALL activation compute —
    embed, MLP, logits — sequence-sharded, not just the attention)."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if input_sharding is not None:
        inputs = jax.lax.with_sharding_constraint(inputs, input_sharding)
        targets = jax.lax.with_sharding_constraint(targets, input_sharding)
    positions = jnp.broadcast_to(jnp.arange(inputs.shape[1]), inputs.shape)
    logits, _ = forward(
        params, cfg, inputs, positions, cache=None, use_flash=False, attn_impl=attn_impl
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.01,
    seq_attn: str = "auto",
    n_microbatch: int | None = None,
):
    """Returns (init_fn, step_fn), both jitted with mesh shardings.

    ``seq_attn`` selects the attention for sequence-parallel meshes
    (sp > 1): "ring" rotates KV blocks around the sp axis with ppermute
    (parallel/ring_attention.py — sequences longer than one device holds),
    "ulysses" all-to-alls heads (sp ≤ kv_heads, cheaper when the full
    sequence fits per device), "auto" picks ulysses when it divides the
    KV heads, else ring; "none" leaves attention to GSPMD propagation.

    A mesh with pp > 1 pipelines the layer stack instead (GPipe-style,
    parallel/pipeline.py): each stage holds L/pp layers, ``n_microbatch``
    microbatches stream through with collective_permute between stages.
    """
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    sp = int(mesh.shape.get("sp", 1))
    pp = int(mesh.shape.get("pp", 1))
    attn_impl = None
    if sp == 1 and pp == 1:
        # non-sequence-parallel meshes: Pallas flash forward per device via
        # shard_map (reference-VJP backward) instead of the einsum path's
        # f32 [B,KV,G,T,S] score materialization (VERDICT r2 weak #2)
        from .parallel.flash_mesh import make_trainable_causal_attention, resolve_mesh_flash

        interp = resolve_mesh_flash(cfg, int(mesh.shape.get("tp", 1)))
        if interp is not None:
            attn_impl = make_trainable_causal_attention(mesh, interpret=interp)
    if sp > 1 and seq_attn != "none":
        if seq_attn == "auto":
            seq_attn = "ulysses" if cfg.n_kv_heads % sp == 0 else "ring"
        if seq_attn == "ulysses":
            from .parallel.ulysses import ulysses_attention

            def attn_impl(q, k, v):
                return ulysses_attention(q, k, v, mesh, axis="sp", batch_axis="dp")

        elif seq_attn == "ring":
            from .parallel.ring_attention import ring_attention

            def attn_impl(q, k, v):
                return ring_attention(q, k, v, mesh, axis="sp", batch_axis="dp")

        else:
            raise ValueError(f"unknown seq_attn {seq_attn!r}")
    repl = NamedSharding(mesh, P())
    if pp > 1:
        from .parallel.pipeline import make_pipeline_loss, pipeline_param_specs

        if cfg.n_layers % pp:
            raise ValueError(f"pp={pp} must divide n_layers={cfg.n_layers}")
        # pp composes with dp (dp-sharded microbatch tokens) and tp
        # (Megatron widths under GSPMD inside the partial-manual shard_map);
        # sp/ep inside a pipeline stage remain future work — refuse rather
        # than silently replicate
        others = {a: int(mesh.shape.get(a, 1)) for a in ("sp", "ep")}
        if any(v > 1 for v in others.values()):
            raise ValueError(
                f"pipeline parallelism does not compose with {others} yet; "
                "use a dp×tp×pp mesh"
            )
        tp_size = int(mesh.shape.get("tp", 1))
        p_shard = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            pipeline_param_specs(cfg.is_moe, tp=tp_size > 1),
            is_leaf=lambda x: isinstance(x, P),
        )
        data = NamedSharding(mesh, P("dp", None))  # dp-sharded tokens
        compute_loss = make_pipeline_loss(cfg, mesh, n_microbatch)
    else:
        p_shard = param_shardings(mesh, moe=cfg.is_moe)
        # sp runs: tokens are [B, T+1] and T+1 need not divide by sp — place
        # them dp-sharded and let loss_fn re-shard the T-long slice over sp
        data = NamedSharding(mesh, P("dp", None) if sp > 1 else batch_spec())
        input_sharding = NamedSharding(mesh, batch_spec()) if sp > 1 else None

        def compute_loss(params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
            return loss_fn(params, cfg, tokens, attn_impl, input_sharding)

    def step(state: TrainState, tokens: jnp.ndarray) -> tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(compute_loss)(state.params, tokens)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    # optimizer state mirrors param sharding; scalars replicate
    def opt_shardings(opt_state):
        def leaf_shard(leaf):
            return repl

        return jax.tree.map(leaf_shard, opt_state)

    def init_sharded(key: jax.Array) -> TrainState:
        params = jax.device_put(init_params(cfg, key, dtype=jnp.float32), p_shard)
        # adamw moments are param-shaped: shard them like their params;
        # scalar leaves (step counts) replicate
        def place_momentlike(leaf):
            if isinstance(leaf, dict) and set(leaf) == set(p_shard):
                return jax.device_put(leaf, p_shard)
            return jax.device_put(leaf, repl)

        opt_state = jax.tree.map(
            place_momentlike,
            tx.init(params),
            is_leaf=lambda x: isinstance(x, dict) and set(x) == set(p_shard),
        )
        return TrainState(params, opt_state, jax.device_put(jnp.zeros((), jnp.int32), repl))

    # input shardings are inferred from the committed arrays; shard_batch
    # places tokens over (dp, sp)
    step_jit = jax.jit(step, donate_argnums=(0,))

    def shard_batch(tokens: jnp.ndarray) -> jnp.ndarray:
        return jax.device_put(tokens, data)

    return init_sharded, step_jit, shard_batch
