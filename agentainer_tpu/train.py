"""Sharded training step — next-token LM loss over a (dp, tp, sp, ep) mesh.

The reference has no training; agents there are frozen external APIs. Here
agents are models the framework owns, so fine-tuning them in place is a
framework feature — and this module is also the multi-chip contract the
driver dry-runs (``__graft_entry__.dryrun_multichip``): params sharded per
parallel/sharding.py, batch sharded over dp×sp, optimizer state sharded like
the params, one jit containing forward, loss, backward, and the optax update
— XLA/GSPMD inserts the gradient all-reduces over ICI.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.configs import ModelConfig
from .models.llama import forward, init_params
from .parallel.sharding import batch_spec, param_shardings


class TrainState(NamedTuple):
    params: dict
    opt_state: Any
    step: jnp.ndarray


def loss_fn(params: dict, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1]."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    positions = jnp.broadcast_to(jnp.arange(inputs.shape[1]), inputs.shape)
    logits, _ = forward(params, cfg, inputs, positions, cache=None, use_flash=False)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.01,
):
    """Returns (init_fn, step_fn), both jitted with mesh shardings."""
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    p_shard = param_shardings(mesh, moe=cfg.is_moe)
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, batch_spec())

    def step(state: TrainState, tokens: jnp.ndarray) -> tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, cfg, tokens)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    # optimizer state mirrors param sharding; scalars replicate
    def opt_shardings(opt_state):
        def leaf_shard(leaf):
            return repl

        return jax.tree.map(leaf_shard, opt_state)

    def init_sharded(key: jax.Array) -> TrainState:
        params = jax.device_put(init_params(cfg, key, dtype=jnp.float32), p_shard)
        # adamw moments are param-shaped: shard them like their params;
        # scalar leaves (step counts) replicate
        def place_momentlike(leaf):
            if isinstance(leaf, dict) and set(leaf) == set(p_shard):
                return jax.device_put(leaf, p_shard)
            return jax.device_put(leaf, repl)

        opt_state = jax.tree.map(
            place_momentlike,
            tx.init(params),
            is_leaf=lambda x: isinstance(x, dict) and set(x) == set(p_shard),
        )
        return TrainState(params, opt_state, jax.device_put(jnp.zeros((), jnp.int32), repl))

    # input shardings are inferred from the committed arrays; shard_batch
    # places tokens over (dp, sp)
    step_jit = jax.jit(step, donate_argnums=(0,))

    def shard_batch(tokens: jnp.ndarray) -> jnp.ndarray:
        return jax.device_put(tokens, data)

    return init_sharded, step_jit, shard_batch
