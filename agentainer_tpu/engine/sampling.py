"""Token sampling: greedy / temperature / top-k / top-p.

Static-shape, jit-safe (no data-dependent branches): filters are applied as
masks over the full vocab so the same compiled sampler serves every request
in a continuous batch with per-request settings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SamplingParams(NamedTuple):
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    top_p: float = 1.0  # 1 → disabled


def sample(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray | float = 0.0,  # scalar or [B]
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Returns token ids [B]. temperature may be per-request ([B]) so one
    batch can mix greedy and sampled requests."""
    temperature = jnp.asarray(temperature, dtype=jnp.float32)
    if temperature.ndim == 0:
        temperature = jnp.broadcast_to(temperature, (logits.shape[0],))

    greedy = jnp.argmax(logits, axis=-1)

    filtered = logits
    if top_k > 0:
        # clamp to the vocab: [:, -top_k] with top_k > V wraps around to an
        # arbitrary mid-distribution threshold and silently corrupts the
        # filter; top_k >= V must mean "disabled" (every token kept)
        k = min(int(top_k), logits.shape[-1])
        kth = jnp.sort(filtered, axis=-1)[:, -k][:, None]
        filtered = jnp.where(filtered < kth, NEG_INF, filtered)
    if top_p < 1.0:
        sorted_logits = jnp.sort(filtered, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        filtered = jnp.where(filtered < cutoff_logit, NEG_INF, filtered)

    scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)
