"""Token sampling: greedy / temperature / top-k / top-p.

Static-shape, jit-safe (no data-dependent branches): filters are applied as
masks over the full vocab so the same compiled sampler serves every request
in a continuous batch with per-request settings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SamplingParams(NamedTuple):
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    top_p: float = 1.0  # 1 → disabled


def sample(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray | float = 0.0,  # scalar or [B]
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Returns token ids [B]. temperature may be per-request ([B]) so one
    batch can mix greedy and sampled requests."""
    temperature = jnp.asarray(temperature, dtype=jnp.float32)
    if temperature.ndim == 0:
        temperature = jnp.broadcast_to(temperature, (logits.shape[0],))

    greedy = jnp.argmax(logits, axis=-1)

    filtered = logits
    if top_k > 0:
        # clamp to the vocab: [:, -top_k] with top_k > V wraps around to an
        # arbitrary mid-distribution threshold and silently corrupts the
        # filter; top_k >= V must mean "disabled" (every token kept)
        k = min(int(top_k), logits.shape[-1])
        kth = jnp.sort(filtered, axis=-1)[:, -k][:, None]
        filtered = jnp.where(filtered < kth, NEG_INF, filtered)
    if top_p < 1.0:
        sorted_logits = jnp.sort(filtered, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        filtered = jnp.where(filtered < cutoff_logit, NEG_INF, filtered)

    scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def sample_step(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32, 0 → greedy
    top_k: jnp.ndarray,  # [B] int32, <= 0 → disabled
    top_p: jnp.ndarray,  # [B] float32, >= 1 → disabled
    *,
    greedy_cond: bool = True,
) -> jnp.ndarray:
    """The fused-loop sampler: every filter is a per-lane ARRAY so a single
    compiled while_loop body serves a batch mixing greedy, temperature,
    top-k, and top-p lanes.

    Bit-exact with :func:`sample`: when a lane's filter is disabled the
    ``where`` keeps the original logit row untouched (not a recomputed
    copy), and when a filter is active the threshold math is the same
    sort-based mask — so `sample(logits, key, t, k, p)` and
    `sample_step(logits, key, [t]*B, [k]*B, [p]*B)` draw identical tokens
    from identical keys.

    The all-greedy batch (the dominant agentic case, and every batch whose
    sampled lanes are parked) takes a ``lax.cond`` fast path: per-lane
    filters as ARRAYS mean the sorts/softmax/threefry below can't be
    constant-folded away like scalar ``sample``'s can, and paying two
    [B, V] sorts plus a categorical draw per decode step to then discard
    them lane-by-lane roughly doubles the per-step wall. Greedy ignores
    the filters anyway (argmax is invariant under top-k/top-p masks), so
    the branch is exact, not approximate.

    ``greedy_cond=False`` (static) drops the ``lax.cond`` and always runs
    the where-merged pipeline — bit-identical output, just no fast path.
    MESHED engines must pass it: this jaxlib's XLA:CPU partitioner
    segfaults compiling a batch-wide conditional over sharded operands
    (pp/sp/tp warmup died inside the cond), and on a real mesh the sort
    pipeline is cheap relative to the sharded forward anyway.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        # top-k as a mask: k_eff clamps into [1, V] (clamp-to-vocab
        # semantics of sample()); kth = the k-th largest logit =
        # ascending-sorted[V - k].
        asc = jnp.sort(logits, axis=-1)
        k_eff = jnp.clip(top_k.astype(jnp.int32), 1, V)
        kth = jnp.take_along_axis(asc, (V - k_eff)[:, None], axis=-1)  # [B, 1]
        k_on = (top_k > 0)[:, None]
        filtered = jnp.where(k_on & (logits < kth), NEG_INF, logits)

        # top-p on the (possibly top-k-filtered) row, gated per lane
        sorted_logits = jnp.sort(filtered, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        p_on = (top_p < 1.0)[:, None]
        filtered = jnp.where(p_on & (filtered < cutoff_logit), NEG_INF, filtered)

        scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
        sampled = jax.random.categorical(key, scaled, axis=-1)
        return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)

    if not greedy_cond:
        return _sampled(None)
    return jax.lax.cond(jnp.all(temperature <= 0.0), lambda _: greedy, _sampled, None)
