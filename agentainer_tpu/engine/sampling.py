"""Token sampling: greedy / temperature / top-k / top-p.

Static-shape, jit-safe (no data-dependent branches): filters are applied as
masks over the full vocab so the same compiled sampler serves every request
in a continuous batch with per-request settings.

Cost model: the exact path pays ONE descending [B, V] sort shared by the
top-k threshold and the top-p cumulative (the two filters used to sort
twice; masking the already-sorted row with the top-k threshold produces
exactly ``jnp.sort(filtered)[::-1]``, so the second sort was pure waste).
The opt-in ``approx_topk`` path replaces the sort entirely with
``jax.lax.approx_max_k`` over a fixed ``APPROX_SEG``-wide segment.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Segment width for the opt-in `approx_topk` sampler path: both the top-k
# threshold and the top-p cumulative operate over the approx_max_k segment
# instead of the full vocab. 128 covers every practical top_k setting; lanes
# asking for top_k > APPROX_SEG are clamped to the segment (a strictly
# stronger filter), and top-p renormalizes over the segment's mass (tail
# mass outside the segment counts as zero, so the cutoff lands at or above
# the exact one — again strictly stronger). Divergence is bounded by the
# probability mass outside the top APPROX_SEG candidates, which for peaked
# LLM logits is negligible; the parity tests pin this.
APPROX_SEG = 128


class SamplingParams(NamedTuple):
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    top_p: float = 1.0  # 1 → disabled


def sample(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray | float = 0.0,  # scalar or [B]
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Returns token ids [B]. temperature may be per-request ([B]) so one
    batch can mix greedy and sampled requests."""
    temperature = jnp.asarray(temperature, dtype=jnp.float32)
    if temperature.ndim == 0:
        temperature = jnp.broadcast_to(temperature, (logits.shape[0],))

    greedy = jnp.argmax(logits, axis=-1)

    filtered = logits
    desc = None
    if top_k > 0 or top_p < 1.0:
        # one shared descending sort serves both filters
        desc = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k > 0:
        # clamp to the vocab: top_k >= V must mean "disabled" (every token
        # kept) — an unclamped k would index out of the row
        k = min(int(top_k), logits.shape[-1])
        kth = desc[:, k - 1][:, None]
        filtered = jnp.where(filtered < kth, NEG_INF, filtered)
        # masking the SORTED row below kth is elementwise identical to
        # jnp.sort(filtered)[::-1]: the kept prefix is untouched and the
        # dropped suffix becomes NEG_INF, in place
        desc = jnp.where(desc < kth, NEG_INF, desc)
    if top_p < 1.0:
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(desc, cutoff_idx[:, None], axis=-1)
        filtered = jnp.where(filtered < cutoff_logit, NEG_INF, filtered)

    scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def sample_step(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32, 0 → greedy
    top_k: jnp.ndarray,  # [B] int32, <= 0 → disabled
    top_p: jnp.ndarray,  # [B] float32, >= 1 → disabled
    *,
    greedy_cond: bool = True,
    approx_topk: bool = False,
) -> jnp.ndarray:
    """The fused-loop sampler: every filter is a per-lane ARRAY so a single
    compiled while_loop body serves a batch mixing greedy, temperature,
    top-k, and top-p lanes.

    Bit-exact with :func:`sample`: when a lane's filter is disabled the
    ``where`` keeps the original logit row untouched (not a recomputed
    copy), and when a filter is active the threshold math is the same
    sort-based mask — so `sample(logits, key, t, k, p)` and
    `sample_step(logits, key, [t]*B, [k]*B, [p]*B)` draw identical tokens
    from identical keys.

    The all-greedy batch (the dominant agentic case, and every batch whose
    sampled lanes are parked) takes a ``lax.cond`` fast path: per-lane
    filters as ARRAYS mean the sort/softmax/threefry below can't be
    constant-folded away like scalar ``sample``'s can, and paying a full
    [B, V] sort plus a categorical draw per decode step to then discard
    them lane-by-lane roughly doubles the per-step wall. Greedy ignores
    the filters anyway (argmax is invariant under top-k/top-p masks), so
    the branch is exact, not approximate.

    ``greedy_cond=False`` (static) drops the ``lax.cond`` and always runs
    the where-merged pipeline — bit-identical output, just no fast path.
    MESHED engines must pass it: this jaxlib's XLA:CPU partitioner
    segfaults compiling a batch-wide conditional over sharded operands
    (pp/sp/tp warmup died inside the cond), and on a real mesh the sort
    pipeline is cheap relative to the sharded forward anyway.

    ``approx_topk=True`` (static) swaps the full-vocab sort for a
    ``jax.lax.approx_max_k`` segment of width :data:`APPROX_SEG`: the
    top-k threshold and the top-p cumulative both come from the segment.
    NOT bit-exact for sampled lanes (see APPROX_SEG notes) — greedy lanes
    are unaffected (argmax never touches the filters). Opt-in via the
    engine's `approx_topk` flag; exact remains the default.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _finish(filtered):
        scaled = filtered / jnp.maximum(temperature, 1e-6)[:, None]
        sampled = jax.random.categorical(key, scaled, axis=-1)
        return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)

    def _exact(_):
        # ONE shared descending sort: the top-k threshold reads it at
        # [k_eff - 1], and masking it below kth reproduces
        # jnp.sort(filtered)[::-1] for the top-p cumulative (the kept
        # prefix is untouched, the dropped suffix becomes NEG_INF).
        desc = jnp.sort(logits, axis=-1)[:, ::-1]
        k_eff = jnp.clip(top_k.astype(jnp.int32), 1, V)
        kth = jnp.take_along_axis(desc, (k_eff - 1)[:, None], axis=-1)  # [B, 1]
        k_on = (top_k > 0)[:, None]
        filtered = jnp.where(k_on & (logits < kth), NEG_INF, logits)
        sorted_logits = jnp.where(k_on & (desc < kth), NEG_INF, desc)

        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        p_on = (top_p < 1.0)[:, None]
        filtered = jnp.where(p_on & (filtered < cutoff_logit), NEG_INF, filtered)
        return _finish(filtered)

    def _approx(_):
        seg = min(V, APPROX_SEG)
        # values arrive sorted descending (aggregate_to_topk=True default);
        # on non-TPU backends approx_max_k lowers to exact top_k, so the
        # only divergence source is the segment truncation itself.
        vals, _ = jax.lax.approx_max_k(logits, k=seg)
        k_eff = jnp.clip(top_k.astype(jnp.int32), 1, seg)
        kth = jnp.take_along_axis(vals, (k_eff - 1)[:, None], axis=-1)  # [B, 1]
        k_on = (top_k > 0)[:, None]
        filtered = jnp.where(k_on & (logits < kth), NEG_INF, logits)
        seg_sorted = jnp.where(k_on & (vals < kth), NEG_INF, vals)

        # top-p over the segment's renormalized mass; the cutoff index is
        # clamped into the segment so a flat distribution (cum never
        # reaching top_p inside the segment) degrades to keep-the-segment
        # rather than reading past it
        probs = jax.nn.softmax(seg_sorted, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.minimum(
            jnp.sum(cum < top_p[:, None], axis=-1), seg - 1
        )  # [B]
        cutoff_logit = jnp.take_along_axis(
            seg_sorted, cutoff_idx[:, None], axis=-1
        )
        p_on = (top_p < 1.0)[:, None]
        filtered = jnp.where(p_on & (filtered < cutoff_logit), NEG_INF, filtered)
        return _finish(filtered)

    _sampled = _approx if approx_topk else _exact

    if not greedy_cond:
        return _sampled(None)
    return jax.lax.cond(jnp.all(temperature <= 0.0), lambda _: greedy, _sampled, None)
