"""Tokenizers for the serving engine.

The TPU-VM image has no model assets and no egress, so the default is a
self-contained byte-level tokenizer (any vocab ≥ 259 works, ids are stable
across runs — important because conversation/KV state persists in the store
across engine restarts). When a checkpoint directory carries a HuggingFace
``tokenizer.json``, the real BPE is used instead (the ``tokenizers`` wheel
is baked into the image).
"""

from __future__ import annotations

import os


class ByteTokenizer:
    """utf-8 bytes shifted by 3 specials: 0=pad, 1=bos, 2=eos."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _OFFSET = 3

    def __init__(self, vocab_size: int):
        if vocab_size < 256 + self._OFFSET:
            raise ValueError(f"vocab {vocab_size} too small for byte tokenizer")
        self.vocab_size = vocab_size

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i - self._OFFSET for i in ids if i >= self._OFFSET and i - self._OFFSET < 256)
        return data.decode("utf-8", "replace")


class HFTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer

        self._tok = Tokenizer.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.pad_id = 0
        self.bos_id = self._tok.token_to_id("<|begin_of_text|>") or 1
        self.eos_id = self._tok.token_to_id("<|end_of_text|>") or 2

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode([i for i in ids if i not in (self.pad_id, self.bos_id, self.eos_id)])


def load_tokenizer(vocab_size: int, checkpoint: str = ""):
    if checkpoint:
        cand = os.path.join(checkpoint, "tokenizer.json")
        if os.path.isfile(cand):
            return HFTokenizer(cand)
    return ByteTokenizer(vocab_size)
