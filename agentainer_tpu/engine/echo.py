"""Mock-LLM echo engine — HTTP-contract parity with the reference example
agents (examples/gpt-agent/app.py), minus the external LLM API.

Routes (app.py:32-179): ``GET /`` info, ``GET /health``, ``POST /chat``,
``GET /history``, ``POST /clear``, ``GET /metrics``. Conversation turns are
persisted through the control plane's store (the reference keeps them in
Redis at ``agent:{AGENT_ID}:conversations`` trimmed to 50, app.py:50-68) so
history survives an engine crash — this is BASELINE.json config #1 and the
baseline workload for the proxy/journal benchmark.

The HTTP layer is a hand-rolled ``asyncio.Protocol`` server rather than an
aiohttp app: this engine IS the benchmark's inner loop, and on the 1-core
control-plane hosts the framework targets, aiohttp's per-request parsing
and response machinery was the single largest CPU consumer of the whole
proxied-chat path. The protocol server parses Content-Length-framed
HTTP/1.1 keepalive requests with two ``find`` calls and writes prebuilt
response frames. (Chunked request bodies are not accepted — the native
proxy always forwards with Content-Length.)
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from ..runtime.store_client import StoreClient

MAX_TURNS = 50  # app.py:58 trim parity


def _frame(status_reason: bytes, body: bytes) -> bytes:
    return (
        b"HTTP/1.1 " + status_reason + b"\r\nContent-Type: application/json\r\n"
        b"Content-Length: " + str(len(body)).encode() + b"\r\n"
        b"Connection: keep-alive\r\n\r\n" + body
    )


_OK = b"200 OK"
_BAD = b"400 Bad Request"
_NF = b"404 Not Found"


class EchoEngine:
    def __init__(self) -> None:
        self.agent_id = os.environ.get("AGENTAINER_AGENT_ID", "standalone")
        self.agent_name = os.environ.get("AGENTAINER_AGENT_NAME", self.agent_id)
        self.store = StoreClient.from_env()
        self.started_at = time.time()
        self.requests_total = 0
        self.chats_total = 0

    @property
    def convo_key(self) -> str:
        return f"agent:{self.agent_id}:conversations"

    @property
    def metrics_key(self) -> str:
        return f"agent:{self.agent_id}:metrics"

    # -- handlers (each returns a complete HTTP response frame) -----------
    def h_root(self) -> bytes:
        return _frame(
            _OK,
            json.dumps(
                {
                    "agent": self.agent_name,
                    "engine": "echo",
                    "status": "running",
                    "endpoints": ["/health", "/chat", "/history", "/clear", "/metrics"],
                }
            ).encode(),
        )

    def h_health(self) -> bytes:
        self.requests_total += 1
        return _frame(
            _OK,
            json.dumps(
                {
                    "status": "healthy",
                    "agent_id": self.agent_id,
                    "uptime_s": time.time() - self.started_at,
                }
            ).encode(),
        )

    async def h_chat(self, body: bytes) -> bytes:
        self.requests_total += 1
        self.chats_total += 1
        try:
            message = str(json.loads(body).get("message", ""))
        except (json.JSONDecodeError, AttributeError):
            return _frame(_BAD, b'{"error": "invalid JSON"}')
        reply = f"Echo: {message}"
        now = time.time()
        try:
            # one pipelined round-trip; rpush returns the post-push length so
            # conversation_length needs no extra llen (ltrim caps it)
            results = await self.store.pipeline(
                [
                    {
                        "op": "rpush",
                        "key": self.convo_key,
                        "values": [
                            json.dumps({"role": "user", "content": message, "ts": now}),
                            json.dumps({"role": "assistant", "content": reply, "ts": now}),
                        ],
                    },
                    {"op": "ltrim", "key": self.convo_key, "start": -2 * MAX_TURNS, "stop": -1},
                    {"op": "hincrby", "key": self.metrics_key, "field": "chats", "amount": 1},
                ]
            )
            n = min(int(results[0]), 2 * MAX_TURNS)
        except Exception:
            n = -1  # store unreachable: still serve (availability over convo durability)
        payload = (
            b'{"response": ' + json.dumps(reply).encode()
            + b', "agent": ' + json.dumps(self.agent_name).encode()
            + b', "conversation_length": ' + str(n).encode() + b"}"
        )
        return _frame(_OK, payload)

    async def h_history(self) -> bytes:
        self.requests_total += 1
        try:
            raw = await self.store.lrange(self.convo_key, 0, -1)
        except Exception:
            raw = []
        turns = []
        for item in raw:
            try:
                turns.append(json.loads(item))
            except json.JSONDecodeError:
                continue
        return _frame(_OK, json.dumps({"history": turns, "count": len(turns)}).encode())

    async def h_clear(self) -> bytes:
        self.requests_total += 1
        try:
            await self.store.delete(self.convo_key)
        except Exception:
            pass
        return _frame(_OK, b'{"status": "cleared"}')

    def h_metrics(self) -> bytes:
        return _frame(
            _OK,
            json.dumps(
                {
                    "engine": "echo",
                    "requests_total": self.requests_total,
                    "chats_total": self.chats_total,
                    "uptime_s": time.time() - self.started_at,
                }
            ).encode(),
        )


class _AccessLog:
    """Batched access log: per-request lines cost one list append; a 200 ms
    flusher writes them to stdout in one syscall. Keeps `logs --follow`
    (docker logs -f parity) seeing per-request activity without paying a
    write+flush syscall pair on every request."""

    def __init__(self) -> None:
        self.lines: list[str] = []
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._flush_loop())

    def add(self, method: bytes, path: bytes, status: int) -> None:
        self.lines.append(
            f"{time.strftime('%H:%M:%S')} access {method.decode('latin1')} "
            f"{path.decode('latin1')} {status}\n"
        )

    async def _flush_loop(self) -> None:
        import sys

        while True:
            await asyncio.sleep(0.2)
            if self.lines:
                batch, self.lines = self.lines, []
                try:
                    sys.stdout.write("".join(batch))
                    sys.stdout.flush()
                except Exception:
                    pass  # broken log pipe: drop the batch, keep serving


_access = _AccessLog()


class _Conn(asyncio.Protocol):
    """One keepalive connection. Requests are parsed from a byte buffer and
    answered IN ORDER (async handlers chain on the previous response so a
    pipelined client can't observe reordering)."""

    __slots__ = ("eng", "tr", "buf", "chain")

    def __init__(self, eng: EchoEngine):
        self.eng = eng
        self.tr = None
        self.buf = b""
        self.chain: asyncio.Future | None = None

    def connection_made(self, transport) -> None:
        self.tr = transport
        try:
            transport.get_extra_info("socket").setsockopt(
                __import__("socket").IPPROTO_TCP, __import__("socket").TCP_NODELAY, 1
            )
        except Exception:
            pass

    def data_received(self, data: bytes) -> None:
        self.buf += data
        while True:
            he = self.buf.find(b"\r\n\r\n")
            if he < 0:
                if len(self.buf) > (1 << 20):  # header flood guard
                    self.tr.close()
                return
            head = self.buf[:he]
            line_end = head.find(b"\r\n")
            first = head if line_end < 0 else head[:line_end]
            parts = first.split(b" ")
            if len(parts) < 3:
                self.tr.close()
                return
            method, target = parts[0], parts[1]
            cl = 0
            if line_end >= 0:
                # anchor at a line start so X-Content-Length (or the value
                # smuggled in the request target) can't desync the framing
                lower = head[line_end:].lower()
                idx = lower.find(b"\r\ncontent-length:")
                if idx >= 0:
                    end = lower.find(b"\r\n", idx + 2)
                    try:
                        cl = int(lower[idx + 17 : end if end >= 0 else None])
                    except ValueError:
                        self.tr.close()
                        return
            total = he + 4 + cl
            if cl < 0 or cl > (64 << 20):
                self.tr.close()
                return
            if len(self.buf) < total:
                return
            body = self.buf[he + 4 : total]
            self.buf = self.buf[total:]
            self._dispatch(method, target, body)

    def _dispatch(self, method: bytes, target: bytes, body: bytes) -> None:
        path = target.split(b"?", 1)[0]
        eng = self.eng
        # sync fast paths write immediately (no task) when nothing is queued
        out: bytes | None = None
        coro = None
        if method == b"POST" and path == b"/chat":
            coro = eng.h_chat(body)
        elif path == b"/health":
            out = eng.h_health()
        elif path == b"/metrics":
            out = eng.h_metrics()
        elif path == b"/history":
            coro = eng.h_history()
        elif method == b"POST" and path == b"/clear":
            coro = eng.h_clear()
        elif path == b"/":
            out = eng.h_root()
        else:
            out = _frame(_NF, b'{"error": "not found"}')
        if coro is None and self.chain is None:
            _access.add(method, path, int(out[9:12]))
            self.tr.write(out)
            return

        prev = self.chain

        async def run() -> None:
            try:
                data = await coro if coro is not None else out
            except BaseException:
                _access.add(method, path, 500)  # failed handlers must log too
                raise
            _access.add(method, path, int(data[9:12]))  # real handler status
            if prev is not None:
                await prev
            tr = self.tr
            if tr is not None and not tr.is_closing():
                tr.write(data)

        task = asyncio.ensure_future(run())
        self.chain = task
        task.add_done_callback(self._chain_done)

    def _chain_done(self, task) -> None:
        if self.chain is task:
            self.chain = None
        if not task.cancelled() and task.exception() is not None and self.tr is not None:
            self.tr.close()  # failed handler: don't leave the client hanging

    def connection_lost(self, exc) -> None:
        self.tr = None


def serve() -> None:
    engine = EchoEngine()
    port = int(os.environ.get("AGENTAINER_PORT", "8000"))

    async def main() -> None:
        loop = asyncio.get_running_loop()
        _access.start()
        server = await loop.create_server(lambda: _Conn(engine), "127.0.0.1", port)
        try:
            await server.serve_forever()
        finally:
            await engine.store.close()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
