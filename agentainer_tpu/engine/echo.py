"""Mock-LLM echo engine — HTTP-contract parity with the reference example
agents (examples/gpt-agent/app.py), minus the external LLM API.

Routes (app.py:32-179): ``GET /`` info, ``GET /health``, ``POST /chat``,
``GET /history``, ``POST /clear``, ``GET /metrics``. Conversation turns are
persisted through the control plane's store (the reference keeps them in
Redis at ``agent:{AGENT_ID}:conversations`` trimmed to 50, app.py:50-68) so
history survives an engine crash — this is BASELINE.json config #1 and the
baseline workload for the proxy/journal benchmark.
"""

from __future__ import annotations

import json
import os
import time

from aiohttp import web

from ..runtime.store_client import StoreClient

MAX_TURNS = 50  # app.py:58 trim parity


class EchoEngine:
    def __init__(self) -> None:
        self.agent_id = os.environ.get("AGENTAINER_AGENT_ID", "standalone")
        self.agent_name = os.environ.get("AGENTAINER_AGENT_NAME", self.agent_id)
        self.store = StoreClient.from_env()
        self.started_at = time.time()
        self.requests_total = 0
        self.chats_total = 0

    @property
    def convo_key(self) -> str:
        return f"agent:{self.agent_id}:conversations"

    @property
    def metrics_key(self) -> str:
        return f"agent:{self.agent_id}:metrics"

    def app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self.h_root)
        app.router.add_get("/health", self.h_health)
        app.router.add_post("/chat", self.h_chat)
        app.router.add_get("/history", self.h_history)
        app.router.add_post("/clear", self.h_clear)
        app.router.add_get("/metrics", self.h_metrics)
        app.on_cleanup.append(lambda _app: self.store.close())
        return app

    async def h_root(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "agent": self.agent_name,
                "engine": "echo",
                "status": "running",
                "endpoints": ["/health", "/chat", "/history", "/clear", "/metrics"],
            }
        )

    async def h_health(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        return web.json_response(
            {"status": "healthy", "agent_id": self.agent_id, "uptime_s": time.time() - self.started_at}
        )

    async def h_chat(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        self.chats_total += 1
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        message = str(body.get("message", ""))
        reply = f"Echo: {message}"
        now = time.time()
        try:
            # one pipelined round-trip; rpush returns the post-push length so
            # conversation_length needs no extra llen (ltrim caps it)
            results = await self.store.pipeline(
                [
                    {
                        "op": "rpush",
                        "key": self.convo_key,
                        "values": [
                            json.dumps({"role": "user", "content": message, "ts": now}),
                            json.dumps({"role": "assistant", "content": reply, "ts": now}),
                        ],
                    },
                    {"op": "ltrim", "key": self.convo_key, "start": -2 * MAX_TURNS, "stop": -1},
                    {"op": "hincrby", "key": self.metrics_key, "field": "chats", "amount": 1},
                ]
            )
            n = min(int(results[0]), 2 * MAX_TURNS)
        except Exception:
            n = -1  # store unreachable: still serve (availability over convo durability)
        return web.json_response(
            {"response": reply, "agent": self.agent_name, "conversation_length": n}
        )

    async def h_history(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        try:
            raw = await self.store.lrange(self.convo_key, 0, -1)
        except Exception:
            raw = []
        turns = []
        for item in raw:
            try:
                turns.append(json.loads(item))
            except json.JSONDecodeError:
                continue
        return web.json_response({"history": turns, "count": len(turns)})

    async def h_clear(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        try:
            await self.store.delete(self.convo_key)
        except Exception:
            pass
        return web.json_response({"status": "cleared"})

    async def h_metrics(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "engine": "echo",
                "requests_total": self.requests_total,
                "chats_total": self.chats_total,
                "uptime_s": time.time() - self.started_at,
            }
        )


def serve() -> None:
    engine = EchoEngine()
    port = int(os.environ.get("AGENTAINER_PORT", "8000"))
    web.run_app(engine.app(), host="127.0.0.1", port=port, print=None)
