"""LLM engine subprocess — serves a JAX prefill+decode engine over the same
HTTP contract as the echo engine (and the reference's example agents,
examples/gpt-agent/app.py:32-179): /chat /health /history /clear /metrics.

The serving stack inside this process:

    aiohttp handlers → continuous-batching scheduler (engine/llm.py)
        → JAX model (models/llama.py; MoE configs via cfg.is_moe) on the
          chips assigned by the slice scheduler (AGENTAINER_CHIPS)

Conversation turns persist through the control plane's store (crash-durable);
the KV-cache can be checkpointed there too (engine/checkpoint.py) so a
restarted engine resumes mid-conversation — BASELINE.json config #3.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from aiohttp import web

from ..runtime.store_client import StoreClient

MAX_TURNS = 50
# per-session conversation lists carry a sliding TTL: session ids are
# client-supplied, so without one every ephemeral session would leave a
# permanent (ltrim-bounded) list behind — the old shared key was bounded
# in TOTAL size, the per-session split must be bounded in key count too
SESSION_CONVO_TTL_S = 7 * 24 * 3600
# proxy ↔ engine wire headers: single definition site shared with the
# control plane (core/protocol.py) — re-exported for existing importers
from ..core.protocol import (  # noqa: E402, F401  (re-export)
    DEADLINE_HEADER,
    DRAINING_HEADER,
    EXPIRED_HEADER,
    LAST_EVENT_ID_HEADER,
    LOADING_HEADER,
    PREFILL_POISON_HEADER,
    STREAM_CONTENT_TYPE,
    STREAM_EVENT_DONE,
    STREAM_EVENT_TOKEN,
)
from .. import faults  # noqa: E402


def _sse_frame(event: str, event_id: int | None, data: dict) -> bytes:
    """One SSE frame: optional ``id:`` (token offset — doubles as the
    client's Last-Event-ID resume cursor), ``event:``, one-line data."""
    lines = [f"event: {event}"]
    if event_id is not None:
        lines.append(f"id: {event_id}")
    lines.append(f"data: {json.dumps(data, separators=(',', ':'))}")
    return ("\n".join(lines) + "\n\n").encode()


class LLMServeApp:
    """One agent's serving surface.

    Normally one per process (env-configured). Under the multi-tenant model
    host (``AGENTAINER_MULTI_TENANT=1``) several instances share ONE process
    and ONE ``LLMEngine`` — one weight copy in HBM for N agents
    (BASELINE.json config #4; VERDICT r4 item 5: separate processes can
    neither share HBM nor even co-open a TPU chip). The host instance owns
    the engine; tenants are attached at runtime via ``/-/tenants`` and
    delegate ``engine``/readiness to the host while keeping their own
    identity: store credentials, conversation keys, KV snapshots, metrics
    counters, persona. Engine sessions are namespaced ``{agent_id}::{sess}``
    so tenants can never touch each other's KV slots.
    """

    def __init__(self, env: dict | None = None, host: "LLMServeApp | None" = None) -> None:
        E = os.environ if env is None else env
        self._host = host
        self._engine = None
        self._engine_error = ""
        self.agent_id = E.get("AGENTAINER_AGENT_ID", "standalone")
        self.agent_name = E.get("AGENTAINER_AGENT_NAME", self.agent_id)
        self.config_name = E.get("AGENTAINER_MODEL_CONFIG", "tiny")
        self.checkpoint = E.get("AGENTAINER_CHECKPOINT", "")
        self.system_prompt = E.get("AGENTAINER_SYSTEM_PROMPT", "")
        # "assistant" flavor: the reference's SECOND example personality
        # (examples/gemini-agent/app.py:87-113): a persona'd agent that
        # FLATTENS its recent store-backed history into one prompt string
        # per turn — stateless model calls, history-in-prompt — instead of
        # the llm flavor's KV-resident sessions
        self.flavor = E.get("AGENTAINER_ENGINE", "llm")
        self.flatten_history = self.flavor == "assistant"
        self.history_turns = 3  # gemini-agent keeps the last 3 exchanges
        try:
            self.model_options = json.loads(E.get("AGENTAINER_MODEL_OPTIONS", "") or "{}")
        except json.JSONDecodeError:
            self.model_options = {}
        # deploy-time persona knobs (usable on the llm flavor too)
        self.flatten_history = self.flatten_history or bool(
            self.model_options.get("flatten_history")
        )
        self.history_turns = int(self.model_options.get("history_turns", self.history_turns))
        if not self.system_prompt:
            self.system_prompt = str(self.model_options.get("system_prompt", ""))
        if self.flavor == "assistant" and not self.system_prompt:
            self.system_prompt = "You are a helpful, concise assistant."
        self.chips = tuple(
            int(c) for c in E.get("AGENTAINER_CHIPS", "0").split(",") if c != ""
        )
        # fleet replica ordinal (0 for single-replica agents): pure
        # observability — lets operators attribute traffic/restarts to one
        # replica in /metrics and logs
        try:
            self.replica = int(E.get("AGENTAINER_REPLICA", "0") or 0)
        except ValueError:
            self.replica = 0
        self.store = StoreClient(
            control_url=E.get("AGENTAINER_CONTROL_URL", ""),
            token=E.get("AGENTAINER_INTERNAL_TOKEN", ""),
            agent_id=E.get("AGENTAINER_AGENT_ID", ""),
            store_sock=E.get("AGENTAINER_STORE_SOCK", ""),
        )
        self.started_at = time.time()
        self.requests_total = 0
        self._ready = asyncio.Event()
        # multi-tenant host state (host instance only)
        self._tenants: dict[str, tuple["LLMServeApp", web.AppRunner, int]] = {}
        self._host_token = E.get("AGENTAINER_HOST_TOKEN", "")
        self.kv_restores = 0
        self.prefix_prewarms = 0
        # tiered KV hierarchy (kv_tiering): proxy-hinted park/prewarm ops
        self.kv_parks = 0
        self.kv_park_errors = 0
        self.kv_prewarms = 0
        self.kv_prewarm_errors = 0
        self.kv_snapshots = 0
        self.kv_snapshots_deferred = 0
        self.kv_snapshot_errors = 0
        self.last_kv_snapshot_error = ""
        # debounce: at most one snapshot per session per interval, with a
        # trailing capture so the END of a burst of turns is still persisted
        # (VERDICT r4 weak #2: per-turn snapshots taxed the device queue the
        # pipelined decode was saturating — 2s TTFT on a healthy decode)
        try:
            self.kv_snapshot_interval_s = float(
                self.model_options.get("kv_snapshot_interval_s", 10.0)
            )
        except (TypeError, ValueError):
            self.kv_snapshot_interval_s = 10.0
        self._kv_last_snap: dict[str, float] = {}
        self._kv_deferred: set[str] = set()
        self.unhandled_errors = 0
        self.last_unhandled_error = ""
        self._bg_tasks: set[asyncio.Task] = set()  # keep snapshot tasks alive
        # graceful-drain state (SIGTERM path): drain budget, outcome, and
        # how many sessions got a final durability snapshot
        try:
            self.drain_budget_s = float(
                self.model_options.get(
                    "drain_budget_s", E.get("AGENTAINER_DRAIN_BUDGET_S", 10.0)
                )
            )
        except (TypeError, ValueError):
            self.drain_budget_s = 10.0
        self.draining = False
        self.drained_clean: bool | None = None
        self.drain_snapshots = 0
        # SSE streaming surface (stream=true on /chat, engine streaming
        # option): keep-alive cadence is configurable per deployment; the
        # env channel covers fleet-wide defaults like the flag quad
        try:
            self.stream_heartbeat_s = float(
                self.model_options.get(
                    "stream_heartbeat_s", E.get("ATPU_STREAM_HEARTBEAT_S", 15.0)
                )
            )
        except (TypeError, ValueError):
            self.stream_heartbeat_s = 15.0
        self.streams_started = 0
        self.stream_tokens_emitted = 0
        self.stream_heartbeats = 0
        self.stream_client_disconnects = 0

    # engine + load state delegate to the host when this app is a tenant:
    # one LLMEngine (one weight copy) serves every attached agent
    @property
    def engine(self):
        return self._host.engine if self._host is not None else self._engine

    @engine.setter
    def engine(self, value) -> None:
        self._engine = value

    @property
    def engine_error(self) -> str:
        return self._host.engine_error if self._host is not None else self._engine_error

    @engine_error.setter
    def engine_error(self, value: str) -> None:
        self._engine_error = value

    @property
    def ready_event(self) -> asyncio.Event:
        return self._host.ready_event if self._host is not None else self._ready

    def _sess(self, session: str) -> str:
        """Engine-side session namespace: tenants sharing one engine must
        never collide on KV slots (or LRU-evict each other's session by
        name)."""
        return f"{self.agent_id}::{session}"

    @property
    def convo_key(self) -> str:
        """Legacy shared conversation list (every session interleaved).
        Still read for backward compatibility; new turns land on the
        per-session keys below."""
        return f"agent:{self.agent_id}:conversations"

    def _convo_session_key(self, session: str) -> str:
        """Per-session conversation list: the flattened-history prompt
        builder reads O(history window) per turn instead of JSON-parsing
        the whole shared list and filtering in Python."""
        return f"{self.convo_key}:{session}"

    def _kv_key(self, session: str) -> str:
        return f"agent:{self.agent_id}:kvcache:{session}"

    def _deadline_from(self, request: web.Request) -> float | None:
        """Absolute give-up instant from the deadline header (remaining ms),
        falling back to the deploy-config default. None = no deadline."""
        raw = request.headers.get(DEADLINE_HEADER, "")
        if not raw:
            raw = self.model_options.get("default_deadline_ms", "")
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            return None
        return time.time() + ms / 1000.0 if ms > 0 else None

    def _policy_response(self, e: BaseException) -> web.Response | None:
        """Map engine lifecycle-policy rejections to HTTP. Returns None for
        anything that is a real error (the json_errors middleware owns it)."""
        from .llm import EngineDraining, EngineOverloaded, RequestCancelled, RequestExpired

        if isinstance(e, EngineOverloaded):
            return web.json_response(
                {"error": str(e), "depth": e.depth, "watermark": e.watermark},
                status=429,
                headers={"Retry-After": str(max(1, int(round(e.retry_after_s))))},
            )
        if isinstance(e, EngineDraining):
            return web.json_response(
                {"error": "engine draining for restart"},
                status=503,
                headers={DRAINING_HEADER: "true", "Retry-After": "5"},
            )
        if isinstance(e, RequestExpired):
            return web.json_response(
                {"error": str(e)}, status=504, headers={EXPIRED_HEADER: "true"}
            )
        if isinstance(e, RequestCancelled):
            # same dead-letter marker as expiry: the proxy must not archive
            # a cancellation notice as the request's completed response
            return web.json_response(
                {"error": str(e)},
                status=499,
                reason="Client Closed Request",
                headers={EXPIRED_HEADER: "true"},
            )
        return None

    async def _snapshot_session(self, session: str) -> None:
        """Fire-and-forget KV snapshot after a turn settles (async host
        offload keeps TTFT out of the snapshot's way — SURVEY.md §7 hard
        part #2). Debounced per session: a burst of turns costs one
        leading snapshot plus one trailing capture, not one per turn."""
        now = time.monotonic()
        last = self._kv_last_snap.get(session)
        if last is not None and now - last < self.kv_snapshot_interval_s:
            if session not in self._kv_deferred:
                self._kv_deferred.add(session)
                try:
                    await asyncio.sleep(last + self.kv_snapshot_interval_s - now)
                finally:
                    self._kv_deferred.discard(session)
            else:
                return  # a deferred capture is already pending; it will see this turn
        await self._snapshot_now(session)

    async def _snapshot_now(self, session: str) -> None:
        from .llm import SnapshotDeferred

        try:
            blob = await self.engine.snapshot_session(self._sess(session))
            if blob:
                self._kv_last_snap[session] = time.monotonic()
                await self.store.set_bytes(self._kv_key(session), blob, ttl=24 * 3600)
                self.kv_snapshots += 1
        except SnapshotDeferred:
            # engine busy / limiter saturated: not an error — the next turn
            # retries, and the engine's snapshot_force_s bounds how long a
            # loaded engine can keep deferring. Counted for observability.
            self.kv_snapshots_deferred += 1
        except Exception as e:
            # surfaced, not swallowed: /metrics carries the count + last error
            self.kv_snapshot_errors += 1
            self.last_kv_snapshot_error = f"{type(e).__name__}: {e}"
            print(f"[llm-serve] kv snapshot failed: {self.last_kv_snapshot_error}", flush=True)

    def _engine_options(self) -> dict:
        opts = dict(self.model_options)
        # fleet-wide speculative-decoding default (config features.speculative
        # → daemon exports ATPU_SPECULATIVE → engine env): per-deployment
        # model options still win
        env_spec = os.environ.get("ATPU_SPECULATIVE")
        if env_spec is not None and "speculative" not in opts:
            opts["speculative"] = env_spec.lower() in ("1", "true", "yes")
        # fleet-wide paged-KV-arena default (config features.paged_kv →
        # daemon exports ATPU_PAGED_KV → engine env); per-deployment model
        # options still win — same channel as speculative above
        env_paged = os.environ.get("ATPU_PAGED_KV")
        if env_paged is not None and "paged_kv" not in opts:
            opts["paged_kv"] = env_paged.lower() in ("1", "true", "yes")
        # remaining engine A/B options ride the identical fleet-default
        # channel (daemon write-back -> engine env -> options, per-deploy
        # model options always winning) — the full quad per flag is
        # machine-checked by analysis rule ATP006
        for flag, env_name in (
            ("adaptive_decode", "ATPU_ADAPTIVE_DECODE"),
            ("prefix_cache", "ATPU_PREFIX_CACHE"),
            ("deadlines", "ATPU_DEADLINES"),
            ("fused_decode", "ATPU_FUSED_DECODE"),
            ("inloop_spec", "ATPU_INLOOP_SPEC"),
            ("approx_topk", "ATPU_APPROX_TOPK"),
            ("kv_tiering", "ATPU_KV_TIERING"),
            ("streaming", "ATPU_STREAMING"),
        ):
            raw = os.environ.get(env_name)
            if raw is not None and flag not in opts:
                opts[flag] = raw.lower() in ("1", "true", "yes")
        if self.chips:
            # no tp injection: LLMEngine.create derives the parallelism
            # split from the chip budget itself (dense → tp-first, MoE →
            # ep-first), and an explicit options.tp/ep/sp only narrows it
            opts["chips"] = list(self.chips)
        # warm boot (engine RESPAWN with a populated persistent XLA cache):
        # skip the serving warmup — every compile it would trigger is a disk
        # cache load that the first real requests absorb in milliseconds,
        # and skipping it is most of the crash-recovery win (VERDICT r4 #4).
        # Gated on a marker proving THIS engine configuration completed a
        # warmup into the cache before — a dir holding only some other
        # model's entries would silently reintroduce full first-request
        # compiles on the recovery path.
        if os.environ.get("AGENTAINER_WARM_BOOT") == "1" and "skip_warmup" not in opts:
            marker = self._warm_marker_path(opts)
            if marker and os.path.exists(marker):
                opts["skip_warmup"] = True
        return opts

    def _warm_marker_path(self, opts: dict) -> str:
        cache_dir = os.environ.get("AGENTAINER_COMPILE_CACHE", "")
        if not cache_dir:
            return ""
        import hashlib

        key = json.dumps(
            {
                "config": self.config_name,
                "checkpoint": self.checkpoint,
                "opts": {k: v for k, v in sorted(opts.items()) if k != "skip_warmup"},
            },
            sort_keys=True,
        )
        return os.path.join(
            cache_dir, f"warmed-{hashlib.sha1(key.encode()).hexdigest()[:16]}"
        )

    def _load_engine(self) -> None:
        """Build the JAX engine (slow: compile + weight init). Runs in a
        thread at startup so /health can answer while loading."""
        try:
            from .llm import LLMEngine

            opts = self._engine_options()
            self.engine = LLMEngine.create(
                config_name=self.config_name,
                checkpoint=self.checkpoint,
                agent_id=self.agent_id,
                store=self.store,
                # deploy-time knobs (quant/max_batch/…); the scheduler's
                # chip assignment always rides along (placement authority),
                # while an explicit options.tp can narrow the span
                options=opts,
            )
            if not opts.get("skip_warmup"):
                # record that THIS configuration's warmup populated the
                # persistent cache — the respawn fast path keys on it
                marker = self._warm_marker_path(opts)
                if marker:
                    try:
                        with open(marker, "w") as f:
                            f.write("ok")
                    except OSError:
                        pass
        except BaseException as e:  # engine stays None; /chat reports 503
            self.engine_error = f"{type(e).__name__}: {e}"

    async def _prewarm_prefix(self) -> None:
        """Register this agent's persona header in the engine's prefix
        arena before traffic arrives: one throwaway 1-token generation of
        ``"{persona}\\n\\n"`` prefills and caches its bucket-prefixes, so
        even the FIRST session forks the persona instead of paying its
        prefill. Matches both serving shapes — the chat path prepends
        ``f"{system_prompt}\\n\\n{message}"`` and the flattened path opens
        with ``f"{system_prompt}\\n\\n{history}"``. Best effort."""
        eng = self.engine
        if eng is None or not self.system_prompt:
            return
        if not getattr(eng, "prefix_cache", False):
            return
        try:
            await eng.generate(
                prompt=f"{self.system_prompt}\n\n", max_tokens=1, temperature=0.0
            )
            self.prefix_prewarms += 1
        except Exception as e:
            print(
                f"[llm-serve] persona prefix prewarm failed for {self.agent_id}: "
                f"{type(e).__name__}: {e}",
                flush=True,
            )

    def _notify_ready(self) -> None:
        """Tell the control plane the model is servable so queued requests
        replay NOW rather than on the next scan tick (loader thread; best
        effort — the 5s replay cadence remains the safety net)."""
        url = self.store.control_url
        token = self.store.token
        if not url or not token:
            return  # standalone runs and identity-less hosts skip the ping
        try:
            import http.client
            from urllib.parse import urlparse

            u = urlparse(url)
            conn = http.client.HTTPConnection(u.hostname, u.port or 80, timeout=5.0)
            conn.request(
                "POST",
                "/internal/engines/ready",
                body=b"{}",
                headers={
                    "X-Agentainer-Agent-ID": self.agent_id,
                    "Authorization": f"Bearer {token}",
                    "Content-Type": "application/json",
                },
            )
            conn.getresponse().read()
            conn.close()
        except Exception as e:
            # best effort, but NEVER fatal: http.client raises more than
            # OSError (BadStatusLine/HTTPException on a garbled response),
            # and this runs on the model-loader thread — an escape here
            # used to kill the loader before the tenant ready fan-out
            # (ADVICE r5); the 5s replay cadence remains the safety net
            print(
                f"[llm-serve] ready callback failed for {self.agent_id}: "
                f"{type(e).__name__}: {e}",
                flush=True,
            )

    def _fan_out_ready(self) -> None:
        """Model-loaded notification for this app AND every attached tenant.
        Per-tenant isolation: one tenant's failing callback must not skip
        the rest (their control planes would all fall back to the replay
        scan cadence)."""
        self._notify_ready()
        for tenant, _, _ in list(self._tenants.values()):
            try:
                tenant._notify_ready()
            except Exception as e:
                print(
                    f"[llm-serve] tenant {tenant.agent_id} ready fan-out "
                    f"failed: {type(e).__name__}: {e}",
                    flush=True,
                )

    def app(self) -> web.Application:
        @web.middleware
        async def json_errors(request: web.Request, handler):
            """Any unhandled handler exception becomes a JSON 500 carrying
            the exception string, with the full traceback in the engine log.
            Round 4's flagship run died with a bare text/plain 500 and no
            surviving diagnostics (VERDICT r4 weak #1) — never again."""
            try:
                return await handler(request)
            except web.HTTPException:
                raise  # intentional status responses pass through
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                import traceback

                self.unhandled_errors += 1
                self.last_unhandled_error = f"{type(e).__name__}: {e}"
                print(
                    f"[llm-serve] {request.method} {request.path} failed:\n"
                    f"{traceback.format_exc()}",
                    flush=True,
                )
                # a typed prefill failure is the request's own fault on a
                # healthy engine: mark the 500 so the proxy charges poison
                # accounting instead of archiving or blaming the engine
                headers = {}
                try:
                    from .llm import PrefillFailed

                    if isinstance(e, PrefillFailed):
                        headers[PREFILL_POISON_HEADER] = "true"
                except ImportError:
                    pass
                return web.json_response(
                    {
                        "error": self.last_unhandled_error,
                        "path": request.path,
                        "agent_id": self.agent_id,
                    },
                    status=500,
                    headers=headers,
                )

        app = web.Application(middlewares=[json_errors])
        app.router.add_get("/", self.h_root)
        app.router.add_get("/health", self.h_health)
        app.router.add_post("/chat", self.h_chat)
        app.router.add_post("/generate", self.h_generate)
        app.router.add_get("/history", self.h_history)
        app.router.add_post("/cancel", self.h_cancel)
        app.router.add_post("/clear", self.h_clear)
        app.router.add_get("/metrics", self.h_metrics)
        app.router.add_post("/profile", self.h_profile)
        # tiered KV hierarchy: the proxy's park/prewarm hints ride the same
        # dispatch path as /chat (journal/fleet semantics apply unchanged)
        app.router.add_post("/park", self.h_park)
        app.router.add_post("/prewarm", self.h_prewarm)
        if self._host_token:
            # multi-tenant host admin surface (localhost-only process; the
            # backend authenticates with the host token it minted at spawn)
            app.router.add_post("/-/tenants", self.h_tenant_attach)
            app.router.add_delete("/-/tenants/{agent_id}", self.h_tenant_detach)

        async def boot(app):
            # Tenants never load: the host's engine is theirs. Their control
            # plane still gets a ready callback (at attach, the host may
            # already be loaded; otherwise the host loader fans out).
            if self._host is not None:
                return
            if self.engine is not None:
                # an engine was injected before startup (embedding, tests):
                # loading again would orphan a second worker thread and
                # race the injected engine out of self.engine
                self._ready.set()
                self._fan_out_ready()
                return
            # DAEMON thread, not asyncio.to_thread: executor threads are
            # joined at interpreter exit, so a load blocked in the TPU
            # runtime (wedged tunnel) would make SIGTERM hang until the
            # backend escalates to SIGKILL — the exact kill that wedges the
            # single-client tunnel for everyone after us. A daemon loader
            # lets a terminated engine die cleanly mid-load.
            import threading

            loop = asyncio.get_running_loop()

            def _run() -> None:
                try:
                    self._load_engine()
                    if self.engine is not None:
                        # persona prefixes into the arena BEFORE ready fans
                        # out: the first replayed request already forks
                        # them (tenants attached mid-load covered here;
                        # later attaches prewarm at attach time)
                        async def _prewarm_all() -> None:
                            await self._prewarm_prefix()
                            for tenant, _, _ in list(self._tenants.values()):
                                await tenant._prewarm_prefix()

                        asyncio.run(_prewarm_all())
                finally:
                    # set even on loader death: waiters unblock
                    loop.call_soon_threadsafe(self._ready.set)
                    if self.engine is not None:
                        self._fan_out_ready()

            threading.Thread(target=_run, daemon=True, name="model-loader").start()

        async def cleanup(app):
            # graceful drain BEFORE detaching tenants: their resident
            # sessions get a final durability snapshot while the engine
            # still holds them — so a rolling restart resumes every
            # tenant's conversation token-identical instead of looking
            # like a crash
            if self._host is None and self.engine is not None:
                await self._graceful_drain()
            for aid in list(self._tenants):
                await self._detach_tenant(aid)
            if self._host is None and self.engine is not None:
                await asyncio.to_thread(self.engine.shutdown)
            await self.store.close()

        app.on_startup.append(boot)
        app.on_cleanup.append(cleanup)
        return app

    async def _graceful_drain(self) -> None:
        """SIGTERM half of a rolling restart: stop admitting, let in-flight
        lanes finish inside the drain budget, then snapshot every resident
        session (the host's AND still-attached tenants') so the respawned
        engine restores them token-identical. Queued journal entries replay
        on respawn — the drain makes a planned restart lossless, not
        crash-shaped."""
        eng = self.engine
        if eng is None:
            return
        self.draining = True
        self.drained_clean = await asyncio.to_thread(eng.drain, self.drain_budget_s)
        # the engine is idle now (or the budget ran out): lift the snapshot
        # limiter — its job is protecting in-flight decode from readback
        # traffic, and there is none left to protect
        eng.snapshot_min_gap_s = 0.0
        eng.snapshot_busy_gap_s = 0.0
        for app_ in [self] + [t for t, _, _ in self._tenants.values()]:
            if not app_.store.connected:
                continue
            prefix = f"{app_.agent_id}::"
            for name in [s for s in list(eng.sessions) if s.startswith(prefix)]:
                before = app_.kv_snapshots
                try:
                    await app_._snapshot_now(name[len(prefix):])
                except Exception:
                    continue  # _snapshot_now already counted/logged it
                if app_.kv_snapshots > before:
                    self.drain_snapshots += 1

    async def h_cancel(self, request: web.Request) -> web.Response:
        """Abort a request by id (the proxy calls this when the client
        disconnects mid-dispatch; operators can too). Queued work is
        rejected before prefill; an in-flight lane is parked mid-decode and
        its slot freed."""
        self.requests_total += 1
        err = await self._ensure_engine()
        if err is not None:
            return err
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        rid = str(body.get("request_id", ""))
        if not rid:
            return web.json_response({"error": "request_id required"}, status=400)
        return web.json_response({"cancelled": bool(self.engine.cancel(rid))})

    # -- multi-tenant host admin (backend-only; VERDICT r4 item 5) --------
    def _check_host_auth(self, request: web.Request) -> bool:
        import hmac as _hmac

        presented = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        return bool(self._host_token) and _hmac.compare_digest(
            presented.encode(), self._host_token.encode()
        )

    async def h_tenant_attach(self, request: web.Request) -> web.Response:
        """Attach an agent to this host: a new serving surface on its own
        localhost port, sharing THIS process's engine (one weight copy)."""
        if not self._check_host_auth(request):
            return web.json_response({"error": "bad host token"}, status=401)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        aid = str(body.get("agent_id", ""))
        if not aid:
            return web.json_response({"error": "agent_id required"}, status=400)
        if aid in self._tenants:  # idempotent re-attach (engine respawn race)
            return web.json_response({"port": self._tenants[aid][2], "existing": True})
        tenant_env = {
            "AGENTAINER_AGENT_ID": aid,
            "AGENTAINER_AGENT_NAME": str(body.get("name", aid)),
            "AGENTAINER_ENGINE": str(body.get("flavor", "llm")),
            "AGENTAINER_MODEL_CONFIG": self.config_name,
            "AGENTAINER_CHECKPOINT": self.checkpoint,
            "AGENTAINER_MODEL_OPTIONS": json.dumps(body.get("options", {}) or {}),
            "AGENTAINER_SYSTEM_PROMPT": str(body.get("system_prompt", "")),
            "AGENTAINER_CONTROL_URL": self.store.control_url,
            "AGENTAINER_INTERNAL_TOKEN": str(body.get("token", "")),
            "AGENTAINER_STORE_SOCK": os.environ.get("AGENTAINER_STORE_SOCK", ""),
            "AGENTAINER_CHIPS": ",".join(map(str, self.chips)),
        }
        tenant = LLMServeApp(env=tenant_env, host=self)
        runner = web.AppRunner(tenant.app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self._tenants[aid] = (tenant, runner, port)
        if self.engine is not None:
            # the tenant's persona goes into the shared engine's prefix
            # arena right away (its first session forks it, same as the
            # host's own persona at boot)
            task = asyncio.ensure_future(tenant._prewarm_prefix())
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
            # model already loaded: replay can drain now. Off-loop: the ping
            # is blocking HTTP and must not stall co-tenants' serving.
            asyncio.get_running_loop().run_in_executor(None, tenant._notify_ready)
        print(f"[llm-serve] tenant {aid} attached on :{port}", flush=True)
        return web.json_response({"port": port})

    async def _detach_tenant(self, aid: str) -> bool:
        entry = self._tenants.pop(aid, None)
        if entry is None:
            return False
        tenant, runner, _ = entry
        if self.engine is not None:
            await asyncio.to_thread(self.engine.clear_sessions, f"{aid}::")
        await runner.cleanup()  # closes the site; tenant cleanup closes its store
        print(f"[llm-serve] tenant {aid} detached", flush=True)
        return True

    async def h_tenant_detach(self, request: web.Request) -> web.Response:
        if not self._check_host_auth(request):
            return web.json_response({"error": "bad host token"}, status=401)
        aid = request.match_info["agent_id"]
        if not await self._detach_tenant(aid):
            return web.json_response({"error": f"no tenant {aid}"}, status=404)
        return web.json_response({"detached": aid, "remaining": len(self._tenants)})

    async def h_root(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "agent": self.agent_name,
                "engine": "llm",
                "model": self.config_name,
                "chips": list(self.chips),
                "status": "running" if self.engine else "loading",
            }
        )

    async def h_health(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        host = self._host if self._host is not None else self
        return web.json_response(
            {
                "status": "draining" if host.draining else "healthy",
                "agent_id": self.agent_id,
                "model_loaded": self.engine is not None,
                "uptime_s": time.time() - self.started_at,
            }
        )

    async def _ensure_engine(self) -> web.Response | None:
        # While the model loads, answer fast with a "loading" marker instead
        # of stalling handlers: the proxy treats it like engine-not-ready
        # (journal entry stays pending, no retry charged, nothing executes
        # twice) and the replay worker re-dispatches once loading finishes.
        # The short bounded wait spares the round-trip when load is nearly
        # done; the Event is set by the loader even if it dies.
        if self.engine is None and not self.engine_error:
            try:
                await asyncio.wait_for(self.ready_event.wait(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
        if self.engine is not None:
            return None
        if self.engine_error:
            return web.json_response(
                {"error": f"model runtime failed to load: {self.engine_error}"}, status=503
            )
        return web.json_response(
            {"error": "model loading"}, status=503, headers={LOADING_HEADER: "true"}
        )

    async def h_chat(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        err = await self._ensure_engine()
        if err is not None:
            return err
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        message = str(body.get("message", ""))
        session = str(body.get("session", "default"))
        max_tokens = int(body.get("max_tokens", 64))
        request_id = request.headers.get("X-Agentainer-Request-ID", "")
        # kwarg only when a deadline is actually set: duck-typed engine
        # doubles (and the echo engine's contract) stay compatible
        dl_kw = (
            {"deadline_at": dl} if (dl := self._deadline_from(request)) is not None else {}
        )
        # fixed-length streams on request (benchmarks and the chaos soak's
        # mid-decode kill need a decode window that doesn't end at a tiny
        # model's early EOS); kwarg-only-when-set, same as deadline_at
        if body.get("ignore_eos"):
            dl_kw["ignore_eos"] = True
        # SSE streaming is opt-in per request AND flag-gated per engine
        # (options.streaming / the ATPU_STREAMING quad): with the flag off,
        # stream=true degrades to today's buffered response — the default
        # path stays byte-identical as the A/B baseline
        stream = bool(body.get("stream")) and bool(
            getattr(self.engine, "streaming", False)
        )

        if self.flatten_history:
            # gemini-agent-style turn: persona + last-N exchanges flattened
            # into ONE prompt string, generated statelessly (no KV session)
            prompt = await self._flattened_prompt(session, message)
            if stream:
                return await self._chat_streamed(
                    request,
                    session=session,
                    message=message,
                    prompt=prompt,
                    max_tokens=max_tokens,
                    request_id=request_id,
                    dl_kw=dl_kw,
                    flatten=True,
                )
            try:
                result = await self.engine.generate(
                    prompt=prompt,
                    max_tokens=max_tokens,
                    request_id=request_id,
                    **dl_kw,
                )
            except Exception as e:
                resp = self._policy_response(e)
                if resp is None:
                    raise
                return resp
            await self._record_turn(session, message, result["text"])
            return web.json_response(
                {
                    "response": result["text"],
                    "agent": self.agent_name,
                    "model": self.config_name,
                    "persona": self.system_prompt,
                    "usage": {
                        "prompt_tokens": result["prompt_tokens"],
                        "completion_tokens": result["completion_tokens"],
                    },
                    "ttft_ms": result.get("ttft_ms"),
                }
            )

        # crash-resume: an unknown session may have a KV snapshot in the
        # store from a previous engine life — restore it before generating
        # so the conversation continues from its exact context. A session
        # parked in the engine's host tier is KNOWN (it promotes at
        # admission) — store-restoring it would resurrect stale context.
        if self.store.connected and not self._engine_has_session(session):
            try:
                blob = await self.store.get_bytes(self._kv_key(session))
                if blob:
                    restored = await self.engine.restore_session(self._sess(session), blob)
                    if restored:
                        self.kv_restores += 1
            except Exception:
                pass

        # persona parity with the reference's SYSTEM_PROMPT env
        # (examples/gpt-agent/app.py): a brand-new session's context opens
        # with the system prompt; later turns inherit it through the KV
        # cache. Only the raw user message goes to /history.
        prompt = message
        if self.system_prompt and not self._engine_has_session(session):
            prompt = f"{self.system_prompt}\n\n{message}"

        if stream:
            return await self._chat_streamed(
                request,
                session=session,
                message=message,
                prompt=prompt,
                max_tokens=max_tokens,
                request_id=request_id,
                dl_kw=dl_kw,
                flatten=False,
            )
        try:
            result = await self.engine.chat(
                session=self._sess(session),
                message=prompt,
                max_tokens=max_tokens,
                request_id=request_id,
                **dl_kw,
            )
        except Exception as e:
            resp = self._policy_response(e)
            if resp is None:
                raise
            return resp
        if self.store.connected:
            task = asyncio.ensure_future(self._snapshot_session(session))
            self._bg_tasks.add(task)  # an unreferenced task can be GC'd mid-flight
            task.add_done_callback(self._bg_tasks.discard)
        await self._record_turn(session, message, result["text"])
        return web.json_response(
            {
                "response": result["text"],
                "agent": self.agent_name,
                "model": self.config_name,
                "usage": {
                    "prompt_tokens": result["prompt_tokens"],
                    "completion_tokens": result["completion_tokens"],
                },
                "ttft_ms": result.get("ttft_ms"),
                "ttft_breakdown": result.get("ttft_breakdown"),
            }
        )

    async def _chat_streamed(
        self,
        request: web.Request,
        *,
        session: str,
        message: str,
        prompt: str,
        max_tokens: int,
        request_id: str,
        dl_kw: dict,
        flatten: bool,
    ) -> web.StreamResponse:
        """SSE token stream for one /chat turn (stream=true).

        Every ``token`` event carries a monotone offset (the ``id:`` line)
        into the request's deterministic token sequence; ``done`` closes
        with the exact payload the buffered path would have returned. The
        offsets are the crash contract: a resume of the SAME journaled
        request re-emits the sequence from offset 0 and this layer skips
        everything at or below the Last-Event-ID splice cursor — so the
        proxy's mid-stream failover (or a reconnecting client) observes one
        gapless, duplicate-free sequence. Comment-frame keep-alives bridge
        long prefills and never advance offsets. A memoized replay returns
        the full result with no live emits; the catch-up loop re-emits it
        under the same offsets, which is exactly what the splice needs.
        """
        self.streams_started += 1
        # engine-side cancel needs an id; direct (proxy-less) clients may
        # not send one
        rid = request_id or f"stream-{time.monotonic_ns()}"
        try:
            last_acked = int(request.headers.get(LAST_EVENT_ID_HEADER, ""))
        except (TypeError, ValueError):
            last_acked = -1
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def emit(start: int, ids: list) -> None:  # worker thread → loop
            loop.call_soon_threadsafe(q.put_nowait, (start, list(ids)))

        if flatten:
            gen = self.engine.generate(
                prompt=prompt,
                max_tokens=max_tokens,
                request_id=rid,
                emit=emit,
                **dl_kw,
            )
        else:
            gen = self.engine.chat(
                session=self._sess(session),
                message=prompt,
                max_tokens=max_tokens,
                request_id=rid,
                emit=emit,
                **dl_kw,
            )
        task = asyncio.ensure_future(gen)

        def _on_done(t: asyncio.Task) -> None:
            if not t.cancelled():
                t.exception()  # mark retrieved; the loop re-reads via result()
            q.put_nowait(("__done__", t))

        task.add_done_callback(_on_done)

        resp: web.StreamResponse | None = None
        tokens: list[int] = []  # engine emission sequence seen so far
        text = ""  # decoded prefix; per-event payload carries the delta
        result = None

        async def ensure_prepared() -> web.StreamResponse:
            nonlocal resp
            if resp is None:
                resp = web.StreamResponse(
                    status=200,
                    headers={
                        "Content-Type": STREAM_CONTENT_TYPE,
                        "Cache-Control": "no-cache",
                        "X-Accel-Buffering": "no",
                    },
                )
                await resp.prepare(request)
            return resp

        async def send_tokens(start: int, ids: list) -> None:
            nonlocal text
            for i, tid in enumerate(ids):
                off = start + i
                if off < len(tokens):
                    continue  # already seen (defensive; the worker is FIFO)
                tokens.append(int(tid))
                new_text = self.engine.tokenizer.decode(tokens)
                delta, text_new = new_text[len(text):], new_text
                text = text_new
                if off <= last_acked:
                    continue  # splice: the consumer already holds this one
                # failpoint: the per-event emission seam — an armed error
                # truncates the stream (no done frame), which is exactly
                # the upstream failure the proxy's failover splice absorbs
                await faults.fire_async("engine.stream")
                r = await ensure_prepared()
                await r.write(
                    _sse_frame(
                        STREAM_EVENT_TOKEN,
                        off,
                        {"offset": off, "token": int(tid), "text": delta},
                    )
                )
                self.stream_tokens_emitted += 1

        try:
            while True:
                try:
                    item = await asyncio.wait_for(
                        q.get(), timeout=max(0.05, self.stream_heartbeat_s)
                    )
                except asyncio.TimeoutError:
                    # keep-alive comment frame: holds idle LB/client
                    # timeouts open through long prefills and tool-call
                    # gaps; carries no id, never advances the cursor
                    r = await ensure_prepared()
                    await r.write(b": keep-alive\n\n")
                    self.stream_heartbeats += 1
                    continue
                if isinstance(item, tuple) and item[0] == "__done__":
                    t = item[1]
                    try:
                        result = t.result()
                    except Exception as e:
                        if resp is None:
                            # nothing sent yet: map to the same statuses as
                            # the buffered path (429/503/504/499, poison
                            # 500s via the middleware) so proxy
                            # classification is unchanged
                            pr = self._policy_response(e)
                            if pr is None:
                                raise
                            return pr
                        # mid-stream failure after bytes went out: close
                        # WITHOUT a done frame — the truncation is the
                        # upstream-failure signal the proxy fails over on
                        return resp
                    break
                await send_tokens(*item)
            # drain emits that landed between the final chunk and done
            while not q.empty():
                item = q.get_nowait()
                if not (isinstance(item, tuple) and item[0] == "__done__"):
                    await send_tokens(*item)
            # memoized replay (and any lost tail): catch up from the
            # result's token list under the same deterministic offsets
            await send_tokens(len(tokens), list(result.get("tokens") or [])[len(tokens):])
            if self.store.connected and not flatten:
                stask = asyncio.ensure_future(self._snapshot_session(session))
                self._bg_tasks.add(stask)
                stask.add_done_callback(self._bg_tasks.discard)
            await self._record_turn(session, message, result["text"])
            payload = {
                "response": result["text"],
                "agent": self.agent_name,
                "model": self.config_name,
                "usage": {
                    "prompt_tokens": result["prompt_tokens"],
                    "completion_tokens": result["completion_tokens"],
                },
                "ttft_ms": result.get("ttft_ms"),
                "ttft_breakdown": result.get("ttft_breakdown"),
            }
            if flatten:
                payload["persona"] = self.system_prompt
            r = await ensure_prepared()
            await r.write(
                _sse_frame(
                    STREAM_EVENT_DONE,
                    len(tokens) - 1 if tokens else None,
                    payload,
                )
            )
            await r.write_eof()
            return r
        except asyncio.CancelledError:
            # aiohttp cancels the handler when the SSE consumer drops:
            # propagate the abort into the engine so the lane frees
            # mid-decode (PR 3's disconnect path, extended to streams)
            self.stream_client_disconnects += 1
            self.engine.cancel(rid)
            raise
        except ConnectionError:
            self.stream_client_disconnects += 1
            self.engine.cancel(rid)
            if resp is not None:
                return resp
            return web.json_response(
                {"error": "client disconnected"},
                status=499,
                reason="Client Closed Request",
            )
        except Exception:
            if resp is None:
                raise  # buffered-style mapping (middleware owns the 500)
            # stream already under way: a clean error response is
            # impossible — cancel the engine side and truncate
            self.engine.cancel(rid)
            return resp

    def _engine_has_session(self, session: str) -> bool:
        """Cross-tier membership: device-resident or parked in the host
        tier. getattr-guarded so duck-typed engine doubles (echo engine,
        test fakes) that only expose ``sessions`` keep working."""
        name = self._sess(session)
        has = getattr(self.engine, "has_session", None)
        if has is not None:
            return bool(has(name))
        return name in self.engine.sessions

    async def h_park(self, request: web.Request) -> web.Response:
        """Tiering hint: demote an idle session off the device (proxy
        policy calls this after a response settles + linger). The exact
        staged blob is persisted to the store as the COLD tier — a parked
        session survives both the host tier's LRU budget and the process."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        session = str(body.get("session", "default"))
        park = getattr(self.engine, "park_session", None)
        if park is None or not getattr(self.engine, "kv_tiering", False):
            return web.json_response({"parked": False, "reason": "tiering off"})
        try:
            blob = await park(self._sess(session))
        except Exception as e:
            self.kv_park_errors += 1
            return web.json_response(
                {"parked": False, "reason": f"{type(e).__name__}: {e}"}
            )
        if blob is None:
            return web.json_response({"parked": False, "reason": "unknown or busy"})
        self.kv_parks += 1
        if self.store.connected:
            try:
                await self.store.set_bytes(self._kv_key(session), blob, ttl=24 * 3600)
                self._kv_last_snap[session] = time.monotonic()
            except Exception as e:
                # host tier still holds the session; only store durability
                # degraded — counted, not fatal
                self.kv_park_errors += 1
                print(
                    f"[llm-serve] park store write failed: {type(e).__name__}: {e}",
                    flush=True,
                )
        return web.json_response({"parked": True, "bytes": len(blob)})

    async def h_prewarm(self, request: web.Request) -> web.Response:
        """Tiering hint: promote a parked session back onto the device
        ahead of its next turn (proxy next-arrival hint). Falls back to a
        store restore when the session fell through to the cold tier."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        session = str(body.get("session", "default"))
        prewarm = getattr(self.engine, "prewarm_session", None)
        if prewarm is None or not getattr(self.engine, "kv_tiering", False):
            return web.json_response({"prewarmed": False, "reason": "tiering off"})
        ok = False
        try:
            ok = bool(await prewarm(self._sess(session)))
        except Exception:
            # best-effort hint: counted; admission still promotes later
            self.kv_prewarm_errors += 1
        if not ok and self.store.connected:
            # cold tier: the host entry was LRU-dropped (or never existed);
            # the store blob restores the exact context instead
            try:
                blob = await self.store.get_bytes(self._kv_key(session))
                if blob:
                    ok = bool(
                        await self.engine.restore_session(self._sess(session), blob)
                    )
                    if ok:
                        self.kv_restores += 1
            except Exception:
                self.kv_prewarm_errors += 1
        if ok:
            self.kv_prewarms += 1
        return web.json_response({"prewarmed": ok})

    async def _record_turn(self, session: str, message: str, reply: str) -> None:
        now = time.time()
        try:
            key = self._convo_session_key(session)
            await self.store.rpush(
                key,
                json.dumps({"role": "user", "content": message, "ts": now, "session": session}),
                json.dumps(
                    {"role": "assistant", "content": reply, "ts": now, "session": session}
                ),
            )
            await self.store.ltrim(key, -2 * MAX_TURNS, -1)
            await self.store.expire(key, SESSION_CONVO_TTL_S)
        except Exception:
            pass

    async def _session_turns(self, session: str, window: int) -> list[dict]:
        """Last ``window`` turns of one session: O(window) read of the
        per-session list, falling back to the legacy shared key (filter by
        session in Python) for conversations recorded before the split."""
        try:
            raw = await self.store.lrange(self._convo_session_key(session), -window, -1)
        except Exception:
            raw = []
        turns = []
        for item in raw:
            try:
                turns.append(json.loads(item))
            except json.JSONDecodeError:
                continue
        if len(turns) >= window:
            return turns
        # window not filled by the per-session list: older turns may still
        # live on the legacy shared key (a conversation recorded before the
        # split must not lose its pre-split context mid-conversation). The
        # legacy read fades out as soon as the per-session list fills.
        legacy_turns = []
        try:
            legacy = await self.store.lrange(self.convo_key, 0, -1)
        except Exception:
            legacy = []
        for item in legacy:
            try:
                t = json.loads(item)
            except json.JSONDecodeError:
                continue
            if t.get("session", "default") == session:
                legacy_turns.append(t)
        return (legacy_turns + turns)[-window:]

    async def _flattened_prompt(self, session: str, message: str) -> str:
        """Persona + the session's last ``history_turns`` exchanges as one
        prompt string (examples/gemini-agent/app.py:87-113 parity). The
        persona + stable history head is also what the engine's prefix
        arena keys on: turn N+1's prompt shares turn N's token prefix up to
        where the window slides, so each turn re-prefills only the tail."""
        lines: list[str] = []
        for t in await self._session_turns(session, 2 * self.history_turns):
            who = "User" if t.get("role") == "user" else "Assistant"
            lines.append(f"{who}: {t.get('content', '')}")
        lines.append(f"User: {message}")
        lines.append("Assistant:")
        history = "\n".join(lines)
        return f"{self.system_prompt}\n\n{history}" if self.system_prompt else history

    async def h_generate(self, request: web.Request) -> web.Response:
        """Raw completion endpoint (no conversation memory)."""
        self.requests_total += 1
        err = await self._ensure_engine()
        if err is not None:
            return err
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        dl_kw = (
            {"deadline_at": dl} if (dl := self._deadline_from(request)) is not None else {}
        )
        try:
            result = await self.engine.generate(
                prompt=str(body.get("prompt", "")),
                max_tokens=int(body.get("max_tokens", 64)),
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                request_id=request.headers.get("X-Agentainer-Request-ID", ""),
                **dl_kw,
            )
        except Exception as e:
            resp = self._policy_response(e)
            if resp is None:
                raise
            return resp
        return web.json_response(result)

    async def h_history(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        turns = []
        try:
            # per-session lists plus the legacy shared key (pre-split
            # turns); merged by timestamp so the combined view reads like
            # the old single list
            keys = [self.convo_key] + sorted(
                await self.store.keys(f"{self.convo_key}:*")
            )
        except Exception:
            keys = [self.convo_key]
        for key in keys:
            try:
                raw = await self.store.lrange(key, 0, -1)
            except Exception:
                continue
            for item in raw:
                try:
                    turns.append(json.loads(item))
                except json.JSONDecodeError:
                    continue
        turns.sort(key=lambda t: t.get("ts", 0.0))
        return web.json_response({"history": turns, "count": len(turns)})

    async def h_clear(self, request: web.Request) -> web.Response:
        self.requests_total += 1
        try:
            await self.store.delete(self.convo_key)
            for key in await self.store.keys(f"{self.convo_key}:*"):
                await self.store.delete(key)
            # KV snapshots must go too, or crash-resume would resurrect the
            # conversation the user just asked to forget
            for key in await self.store.keys(f"agent:{self.agent_id}:kvcache:*"):
                await self.store.delete(key)
        except Exception:
            pass
        if self.engine is not None:
            await asyncio.to_thread(self.engine.clear_sessions, f"{self.agent_id}::")
        return web.json_response({"status": "cleared"})

    async def h_profile(self, request: web.Request) -> web.Response:
        """Capture a jax.profiler trace of live serving (device + host
        timelines). One capture at a time; the trace directory is shared
        with the control plane so the management API can return its path."""
        self.requests_total += 1
        err = await self._ensure_engine()
        if err is not None:
            return err
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}  # empty/absent body → defaults
        if not isinstance(body, dict):
            body = {}
        try:
            # clamp below the control plane's 30 s dispatch timeout: a trace
            # the proxy can't wait out would 502 the caller while the engine
            # completed it anyway (ADVICE r3)
            duration = min(float(body.get("duration_s", 2.0) or 2.0), 25.0)
        except (TypeError, ValueError):
            return web.json_response(
                {"error": 'duration_s must be a number, e.g. {"duration_s": 2.0}'},
                status=400,
            )
        if getattr(self, "_profiling", False):
            return web.json_response({"error": "profile already running"}, status=409)
        trace_dir = os.environ.get("AGENTAINER_PROFILE_DIR", "") or os.path.join(
            "/tmp", f"atpu-profile-{self.agent_id}"
        )
        os.makedirs(trace_dir, exist_ok=True)
        self._profiling = True
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
            try:
                await asyncio.sleep(duration)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            return web.json_response(
                {"error": f"profiler failed: {type(e).__name__}: {e}"}, status=500
            )
        finally:
            self._profiling = False
        return web.json_response(
            {"trace_dir": trace_dir, "duration_s": duration, "agent_id": self.agent_id}
        )

    async def h_metrics(self, request: web.Request) -> web.Response:
        doc = {
            "engine": "llm",
            "model": self.config_name,
            "replica": self.replica,
            "requests_total": self.requests_total,
            "uptime_s": time.time() - self.started_at,
            "model_loaded": self.engine is not None,
            "engine_error": self.engine_error or None,
            "kv_snapshots": self.kv_snapshots,
            "kv_snapshots_deferred": self.kv_snapshots_deferred,
            "kv_restores": self.kv_restores,
            "prefix_prewarms": self.prefix_prewarms,
            "kv_parks": self.kv_parks,
            "kv_park_errors": self.kv_park_errors,
            "kv_prewarms": self.kv_prewarms,
            "kv_prewarm_errors": self.kv_prewarm_errors,
            "kv_snapshot_errors": self.kv_snapshot_errors,
            "last_kv_snapshot_error": self.last_kv_snapshot_error or None,
            "unhandled_errors": self.unhandled_errors,
            "last_unhandled_error": self.last_unhandled_error or None,
            "drain_budget_s": self.drain_budget_s,
            "drained_clean": self.drained_clean,
            "drain_snapshots": self.drain_snapshots,
            "streams_started": self.streams_started,
            "stream_tokens_emitted": self.stream_tokens_emitted,
            "stream_heartbeats": self.stream_heartbeats,
            "stream_client_disconnects": self.stream_client_disconnects,
        }
        if self._host is not None or self._tenants:
            # HBM audit for the sharing demo: engine-level hbm byte counts
            # below are ONE physical copy serving every attached agent
            doc["weights_shared"] = True
            doc["tenants"] = len(
                (self._host._tenants if self._host is not None else self._tenants)
            )
        if self.engine is not None:
            doc.update(self.engine.metrics())
        return web.json_response(doc)


def serve() -> None:
    app_obj = LLMServeApp()
    port = int(os.environ.get("AGENTAINER_PORT", "8000"))
    web.run_app(app_obj.app(), host="127.0.0.1", port=port, print=None)
