"""Checkpoint plane: model weights + KV-cache snapshots.

Three tiers, mirroring and upgrading the reference's checkpoint story
(SURVEY.md §5.4: agent records in Redis, backup tarballs, in-agent
checkpoint patterns):

- **weights**: orbax PyTree checkpoints under a directory; ``load_params``
  restores into the model's pytree with the engine's dtype;
- **KV snapshots**: a single cache *slot* (one session's context) serialized
  to bytes for the store — this is what lets a restarted engine resume a
  conversation without re-prefilling (BASELINE.json config #3);
- agent records/backups live in the control plane (manager/backup.py).
"""

from __future__ import annotations

import io
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig
from ..models.llama import KVCache


def save_params(params: dict, path: str | Path) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).expanduser().resolve()
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path / "params", jax.device_get(params))


def load_params(cfg: ModelConfig, path: str | Path, dtype=jnp.bfloat16) -> dict:
    """Restore weights from either supported layout: an orbax PyTree dir
    (our own save_params) or a HuggingFace checkpoint dir (config.json +
    *.safetensors) via engine/hf_convert.py — the deploy-any-published-
    checkpoint path."""
    path = Path(path).expanduser().resolve()
    from .hf_convert import is_hf_checkpoint, load_hf_params

    if is_hf_checkpoint(path):
        return load_hf_params(cfg, path, dtype)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path / "params")
    # host-side cast: the engine device_puts with its target sharding, so a
    # TP-sharded model never materializes whole on one chip
    return jax.tree.map(lambda x: np.asarray(x).astype(dtype), restored)


# -- KV slot snapshots (engine ↔ store) ---------------------------------
# v2: KV ships in the cache's EXACT dtype (v1 cast everything to fp16,
# which rounded fp32/bf16 arenas on restore and broke the token-identical
# resume guarantee under near-tie greedy argmax). bfloat16 has no portable
# npz encoding (np.savez degrades it to a void dtype), so it travels as a
# uint16 bit-view with the true dtype recorded in the header.
# v3: paged-arena era. The payload layout is UNCHANGED (position-trimmed
# [L, pos, KV, hd] prefix in the exact dtype) — a paged engine stages it
# by gathering only the session's live pages, and the optional
# ``page_size`` header records that provenance — so v3 blobs restore into
# paged and dense engines alike, and v2/v1 blobs written before the
# upgrade keep restoring (the reader accepts all three).
SNAP_VERSION = 3


def pack_kv_snapshot(k16, v16, position: int, meta: dict | None = None) -> bytes:
    """Host half of a KV snapshot: block on the staged device buffers
    (bucket-padded [L, bucket, KV, hd] — the engine's worker dispatched the
    slice), trim to the live prefix, and pack a self-describing npz blob.
    Only the written prefix ships — a 100-token conversation snapshot is
    ~100/S of the slot arena."""
    k = np.asarray(k16)[:, :position]
    v = np.asarray(v16)[:, :position]
    dtype_name = k.dtype.name
    if dtype_name == "bfloat16":
        k, v = k.view(np.uint16), v.view(np.uint16)
    buf = io.BytesIO()
    header = json.dumps(
        {
            "version": SNAP_VERSION,
            "position": position,
            "dtype": dtype_name,
            **(meta or {}),
        }
    )
    np.savez_compressed(buf, k=k, v=v, header=np.frombuffer(header.encode(), dtype=np.uint8))
    return buf.getvalue()


def deserialize_kv_slot(blob: bytes) -> tuple[np.ndarray, np.ndarray, dict]:
    """Returns (k [L, pos, KV, hd], v, header dict) in the snapshot's true
    dtype. Accepts v1 blobs (fp16 payload) so snapshots taken before an
    engine upgrade still restore across it."""
    with np.load(io.BytesIO(blob)) as z:
        header = json.loads(bytes(z["header"]).decode())
        version = header.get("version")
        k, v = z["k"], z["v"]
        if version == 1:
            return k, v, header  # legacy: fp16 as stored
        if version not in (2, SNAP_VERSION):  # v2 fallback: same payload layout
            raise ValueError(f"unsupported KV snapshot version: {version}")
        if header.get("dtype") == "bfloat16":
            import ml_dtypes

            k, v = k.view(ml_dtypes.bfloat16), v.view(ml_dtypes.bfloat16)
        return k, v, header


def restore_kv_slot(cache: KVCache, slot: int, k: np.ndarray, v: np.ndarray) -> KVCache:
    """Write a snapshot back into slot's prefix; rest of the arena unchanged."""
    position = k.shape[1]
    dtype = cache.k.dtype
    new_k = cache.k.at[:, slot, :position].set(jnp.asarray(k, dtype))
    new_v = cache.v.at[:, slot, :position].set(jnp.asarray(v, dtype))
    return KVCache(new_k, new_v)
