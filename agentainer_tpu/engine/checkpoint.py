"""Checkpoint plane: model weights + KV-cache snapshots.

Three tiers, mirroring and upgrading the reference's checkpoint story
(SURVEY.md §5.4: agent records in Redis, backup tarballs, in-agent
checkpoint patterns):

- **weights**: orbax PyTree checkpoints under a directory; ``load_params``
  restores into the model's pytree with the engine's dtype;
- **KV snapshots**: a single cache *slot* (one session's context) serialized
  to bytes for the store — this is what lets a restarted engine resume a
  conversation without re-prefilling (BASELINE.json config #3);
- agent records/backups live in the control plane (manager/backup.py).
"""

from __future__ import annotations

import io
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig
from ..models.llama import KVCache


def save_params(params: dict, path: str | Path) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).expanduser().resolve()
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path / "params", jax.device_get(params))


def load_params(cfg: ModelConfig, path: str | Path, dtype=jnp.bfloat16) -> dict:
    """Restore weights from either supported layout: an orbax PyTree dir
    (our own save_params) or a HuggingFace checkpoint dir (config.json +
    *.safetensors) via engine/hf_convert.py — the deploy-any-published-
    checkpoint path."""
    path = Path(path).expanduser().resolve()
    from .hf_convert import is_hf_checkpoint, load_hf_params

    if is_hf_checkpoint(path):
        return load_hf_params(cfg, path, dtype)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path / "params")
    # host-side cast: the engine device_puts with its target sharding, so a
    # TP-sharded model never materializes whole on one chip
    return jax.tree.map(lambda x: np.asarray(x).astype(dtype), restored)


# -- KV slot snapshots (engine ↔ store) ---------------------------------
SNAP_VERSION = 1


def pack_kv_snapshot(k16, v16, position: int, meta: dict | None = None) -> bytes:
    """Host half of a KV snapshot: block on the staged fp16 device buffers
    (bucket-padded [L, bucket, KV, hd] — the engine's worker dispatched the
    slice), trim to the live prefix, and pack a self-describing npz blob.
    Only the written prefix ships — a 100-token conversation snapshot is
    ~100/S of the slot arena."""
    k = np.asarray(k16)[:, :position]
    v = np.asarray(v16)[:, :position]
    buf = io.BytesIO()
    header = json.dumps({"version": SNAP_VERSION, "position": position, **(meta or {})})
    np.savez_compressed(buf, k=k, v=v, header=np.frombuffer(header.encode(), dtype=np.uint8))
    return buf.getvalue()


def deserialize_kv_slot(blob: bytes) -> tuple[np.ndarray, np.ndarray, dict]:
    """Returns (k [L, pos, KV, hd], v, header dict)."""
    with np.load(io.BytesIO(blob)) as z:
        header = json.loads(bytes(z["header"]).decode())
        if header.get("version") != SNAP_VERSION:
            raise ValueError(f"unsupported KV snapshot version: {header.get('version')}")
        return z["k"], z["v"], header


def restore_kv_slot(cache: KVCache, slot: int, k: np.ndarray, v: np.ndarray) -> KVCache:
    """Write a snapshot back into slot's prefix; rest of the arena unchanged."""
    position = k.shape[1]
    dtype = cache.k.dtype
    new_k = cache.k.at[:, slot, :position].set(jnp.asarray(k, dtype))
    new_v = cache.v.at[:, slot, :position].set(jnp.asarray(v, dtype))
    return KVCache(new_k, new_v)
