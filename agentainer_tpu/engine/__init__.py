"""Serving engines — the programs agents run.

Replaces the reference's user-supplied Docker images (Flask apps calling
external LLM APIs, examples/gpt-agent/app.py). Engines here are in-process
serving programs placed on TPU chips:

- ``echo``       mock-LLM parity agent (engine/echo.py): same HTTP contract
  as examples/gpt-agent (/chat /health /history /clear /metrics),
  conversation memory in the store — BASELINE.json config #1.
- ``llm``        JAX prefill+decode engine with continuous batching
  (engine/llm.py) — BASELINE.json configs #2-#5.
- ``assistant``  persona flavor of the llm engine: system-prompted, with
  recent store-backed history FLATTENED into each turn's prompt — the
  reference's second example personality
  (examples/gemini-agent/app.py:87-113 builds one prompt string from
  history instead of threading structured messages).

The registry is OPEN — the reference accepted any Docker image, so this
framework accepts user engines the same way: ``register_engine`` in
process, or ``ATPU_EXTRA_ENGINES=name:module.path,...`` in the daemon's
environment (each module must expose ``serve()``; engine subprocesses
import it by that path).
"""

from __future__ import annotations

import os

_BUILTIN: dict[str, str] = {
    "echo": "agentainer_tpu.engine.echo",
    "llm": "agentainer_tpu.engine.llm_serve",
    "assistant": "agentainer_tpu.engine.llm_serve",  # persona preset of llm
}

# engines backed by the JAX model runtime: they validate a model config at
# deploy time, share weight HBM by config name, and keep their JAX_PLATFORMS
# (everything else is pinned to CPU so it can't touch the chips). Keyed at
# the registry so flavors can't silently miss a per-call-site name check.
_TPU_BACKED: set[str] = {"llm", "assistant"}

_EXTRA: dict[str, str] = {}


def register_engine(name: str, module: str, tpu: bool = False) -> None:
    """Register a user engine: ``module`` must expose ``serve()`` (run in
    the engine subprocess with the AGENTAINER_* env contract). ``tpu``
    marks it JAX-backed (model-config validation + chip placement)."""
    if not name or ":" in name or "," in name:
        raise ValueError(f"bad engine name {name!r}")
    _EXTRA[name] = module
    if tpu:
        _TPU_BACKED.add(name)


def is_tpu_engine(name: str) -> bool:
    return name in _TPU_BACKED


def _env_engines() -> dict[str, str]:
    out: dict[str, str] = {}
    raw = os.environ.get("ATPU_EXTRA_ENGINES", "")
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, module = part.partition(":")
        if name and module:
            out[name] = module
    return out


def engine_registry() -> dict[str, str]:
    """name → serve-module for every known engine (builtin + registered +
    environment-injected)."""
    reg = dict(_BUILTIN)
    reg.update(_env_engines())
    reg.update(_EXTRA)
    return reg


def known_engines() -> set[str]:
    return set(engine_registry())
