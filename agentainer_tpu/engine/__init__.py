"""Serving engines — the programs agents run.

Replaces the reference's user-supplied Docker images (Flask apps calling
external LLM APIs, examples/gpt-agent/app.py). Engines here are in-process
serving programs placed on TPU chips:

- ``echo``  mock-LLM parity agent (engine/echo.py): same HTTP contract as
  examples/gpt-agent (/chat /health /history /clear /metrics), conversation
  memory in the store — BASELINE.json config #1.
- ``llm``   JAX prefill+decode engine with continuous batching
  (engine/llm.py) — BASELINE.json configs #2-#5.
"""

from __future__ import annotations


def known_engines() -> set[str]:
    return {"echo", "llm"}
