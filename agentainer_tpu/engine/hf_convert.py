"""HuggingFace checkpoint → engine params converter.

This is the TPU-native analogue of the reference's image builder
(pkg/docker/builder.go:98-187: turn a user-supplied artifact into a
runnable image): here the user-supplied artifact is a HF-format Llama /
Mixtral checkpoint directory (config.json + *.safetensors, possibly
sharded), and "building" means mapping it onto the engine's stacked-layer
pytree (models/llama.py) so deploy can point at any published checkpoint.

Weight-name mapping (HF Llama convention → ours). HF stores projections as
[out, in] torch Linear weights; our forward uses x @ W, so every projection
transposes. Our RoPE is the same rotate_half layout HF ships, so q/k need
no permutation.

    model.embed_tokens.weight            → embed                [V, D]
    …layers.{i}.input_layernorm.weight   → layers.attn_norm[i]  [D]
    …layers.{i}.self_attn.{q,k,v}_proj   → wq/wk/wv[i]          [D, H*hd]ᵀ
    …layers.{i}.self_attn.o_proj         → wo[i]                [H*hd, D]ᵀ
    …layers.{i}.post_attention_layernorm → layers.mlp_norm[i]   [D]
    …layers.{i}.mlp.{gate,up,down}_proj  → w_gate/w_up/w_down   ᵀ
    model.norm.weight                    → final_norm           [D]
    lm_head.weight (or tied embeddings)  → lm_head              [D, V]ᵀ

Mixtral MoE:
    …block_sparse_moe.gate               → router[i]            [D, E]ᵀ
    …experts.{e}.w1 / w3 / w2            → w_gate/w_up/w_down[i,e]ᵀ
"""

from __future__ import annotations

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig


def is_hf_checkpoint(path: str | Path) -> bool:
    p = Path(path).expanduser()
    return p.is_dir() and any(p.glob("*.safetensors"))


def _open_shards(path: Path) -> dict:
    """name → (shard_path). Handles single-file and index-sharded layouts."""
    index = path / "model.safetensors.index.json"
    if index.exists():
        weight_map = json.loads(index.read_text())["weight_map"]
        return {name: path / shard for name, shard in weight_map.items()}
    shards = sorted(path.glob("*.safetensors"))
    out: dict[str, Path] = {}
    from safetensors import safe_open

    for shard in shards:
        with safe_open(shard, framework="np") as f:
            for name in f.keys():
                out[name] = shard
    return out


class _Loader:
    """Lazily opens shards; tensors come out as numpy (bf16 via ml_dtypes)."""

    def __init__(self, path: Path):
        self.map = _open_shards(path)
        self._handles: dict[Path, object] = {}

    def __contains__(self, name: str) -> bool:
        return name in self.map

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        shard = self.map[name]
        if shard not in self._handles:
            self._handles[shard] = safe_open(shard, framework="np").__enter__()
        return self._handles[shard].get_tensor(name)


def config_from_hf(path: str | Path) -> ModelConfig:
    """Derive a ModelConfig from the checkpoint's own config.json."""
    doc = json.loads((Path(path).expanduser() / "config.json").read_text())
    n_experts = int(doc.get("num_local_experts", 0) or 0)
    return ModelConfig(
        name=doc.get("model_type", "hf") + "-import",
        vocab_size=int(doc["vocab_size"]),
        dim=int(doc["hidden_size"]),
        n_layers=int(doc["num_hidden_layers"]),
        n_heads=int(doc["num_attention_heads"]),
        n_kv_heads=int(doc.get("num_key_value_heads", doc["num_attention_heads"])),
        ffn_dim=int(doc["intermediate_size"]),
        max_seq_len=int(doc.get("max_position_embeddings", 8192)),
        rope_theta=float(doc.get("rope_theta", 500_000.0)),
        norm_eps=float(doc.get("rms_norm_eps", 1e-5)),
        n_experts=n_experts,
        experts_per_token=int(doc.get("num_experts_per_tok", 2)),
    )


def load_hf_params(
    cfg: ModelConfig, path: str | Path, dtype: jnp.dtype = jnp.bfloat16
) -> dict:
    """Map a HF Llama/Mixtral checkpoint directory onto the engine pytree.

    Returns HOST (numpy, ml_dtypes-backed for bf16) arrays: the engine
    device_puts them with its target sharding, so a TP-sharded model is
    never materialized whole on one chip's HBM — required when the weights
    only fit *because* of TP."""
    p = Path(path).expanduser().resolve()
    ld = _Loader(p)

    def t(name: str) -> np.ndarray:  # torch Linear [out,in] → x@W layout
        return np.asarray(ld.get(name)).astype(dtype).T

    def vec(name: str) -> np.ndarray:
        return np.asarray(ld.get(name)).astype(dtype)

    def stack(fmt: str, transpose: bool = True) -> np.ndarray:
        fn = t if transpose else vec
        return np.stack([fn(fmt.format(i=i)) for i in range(cfg.n_layers)])

    L = "model.layers.{i}."
    layers = {
        "attn_norm": stack(L + "input_layernorm.weight", transpose=False),
        "wq": stack(L + "self_attn.q_proj.weight"),
        "wk": stack(L + "self_attn.k_proj.weight"),
        "wv": stack(L + "self_attn.v_proj.weight"),
        "wo": stack(L + "self_attn.o_proj.weight"),
        "mlp_norm": stack(L + "post_attention_layernorm.weight", transpose=False),
    }
    if cfg.is_moe:
        layers["router"] = stack(L + "block_sparse_moe.gate.weight")

        def experts(w: str) -> np.ndarray:  # [L, E, …]
            return np.stack(
                [
                    np.stack(
                        [
                            t(f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight")
                            for e in range(cfg.n_experts)
                        ]
                    )
                    for i in range(cfg.n_layers)
                ]
            )

        layers["w_gate"] = experts("w1")
        layers["w_down"] = experts("w2")
        layers["w_up"] = experts("w3")
    else:
        layers["w_gate"] = stack(L + "mlp.gate_proj.weight")
        layers["w_up"] = stack(L + "mlp.up_proj.weight")
        layers["w_down"] = stack(L + "mlp.down_proj.weight")

    embed = np.asarray(ld.get("model.embed_tokens.weight")).astype(dtype)
    lm_head = (
        t("lm_head.weight") if "lm_head.weight" in ld else embed.T  # tied
    )
    return {
        "embed": embed,
        "layers": layers,
        "final_norm": vec("model.norm.weight"),
        "lm_head": lm_head,
    }
