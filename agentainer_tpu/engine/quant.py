"""Int8 weight-only quantization for serving (llama-pytree aware).

Why: Llama-3-8B in bf16 is ~16 GB of weights — a whole v5e chip's HBM,
leaving nothing for the KV arena. Weight-only int8 halves that (8 GB), so
the 8B flagship serves on ONE chip with a real cache (BASELINE.json
config #2 without requiring a multi-chip slice), and halves the weight
HBM→VMEM streaming that bounds decode throughput.

Deploy with ``model.options.quant: int8``. Quantization runs host-side
over the checkpoint arrays; norm vectors keep the working dtype (tiny,
precision-critical). Dequantization happens per layer slice inside the
model's scan (models/llama.py) — only the current layer is ever dense.

Core tensor type lives in ops/quant.py (model-agnostic).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.quant import QTensor, dequant, quantize_array  # noqa: F401 (re-export)

# matmul weights to quantize, by pytree key; norms keep their dtype
_QUANT_KEYS = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router",
    "embed", "lm_head",
}


def quantize_params(params: dict, dtype=jnp.bfloat16) -> dict:
    """Quantize the matmul weights of a models/llama.py pytree (host-side
    input recommended — the dense model then never touches HBM)."""
    out: dict = {}
    for key, val in params.items():
        if isinstance(val, dict):
            out[key] = quantize_params(val, dtype)
        elif key in _QUANT_KEYS:
            out[key] = quantize_array(val, dtype)
        else:
            out[key] = jnp.asarray(np.asarray(val).astype(dtype))
    return out


def param_bytes_actual(params: dict) -> int:
    """Byte footprint of the (possibly quantized) pytree."""
    import jax

    return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(params))
