"""Continuous-batching JAX inference engine — the heart of the data plane.

Replaces the reference's external LLM calls (examples/gpt-agent/app.py:98-109
POSTs to OpenAI) with an in-process prefill+decode engine on the agent's
TPU chips (BASELINE.json configs #2/#3). TPU-first design decisions:

- **one compiled decode step, static shapes**: a fixed slot-batch
  ``[max_batch]`` decodes every active sequence each step at its own cache
  position (ragged positions via the model's scatter cache); idle slots
  write to a reserved scratch slot — no recompiles as requests come and go;
- **bucketed prefill**: prompts pad up to power-of-two buckets so prefill
  compiles a handful of shapes, padding writes land on positions later
  overwritten before any query can attend to them;
- **TTFT = prefill**: the first token is sampled from the prefill logits,
  so time-to-first-token is one prefill pass, not prefill + a decode step;
- **sessions own KV**: a chat session keeps its cache slot between turns
  (multi-turn TTFT stays flat); idle sessions evict LRU when slots run out;
- **idempotent by request id**: completed results are memoized, so a
  journal replay that races the original returns the stored result instead
  of generating twice (the engine-side half of the crash-replay contract).

The engine runs its JAX work on a dedicated worker thread; the aiohttp
handlers (engine/llm_serve.py) talk to it through a thread-safe queue and
asyncio futures.
"""

from __future__ import annotations

import asyncio
import collections
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Partitionable threefry, set before any engine program is traced: the
# legacy (non-partitionable) implementation computes WRONG values when a
# random-init is jitted with out_shardings over a mesh with more than one
# nontrivial axis and a spec that uses only a subset of them (jax 0.4.37:
# P("tp", None) on a tp×sp mesh silently corrupts the embed table — the
# tp×sp engine decoded garbage while tp-only and sp-only were fine).
# Partitionable threefry is sharding-invariant by construction. It changes
# the random stream, so every in-process engine/model comparison shares
# the new stream; no test pins absolute values from the old one.
jax.config.update("jax_threefry_partitionable", True)

from .. import faults
from ..models.configs import ModelConfig, get_config
from ..models.llama import KVCache, PagedKVCache, forward, init_params
from .sampling import NEG_INF, sample, sample_step
from .tokenizer import load_tokenizer

PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024)

# Paged KV arena (block tables): the pool's page granularity in tokens.
# 64 keeps every PREFILL_BUCKET level ≥ 64 page-aligned (zero-copy prefix
# sharing with no partial tail) while a near-empty session pins one page,
# not a whole max_seq slot.
PAGE_SIZE_DEFAULT = 64

# Self-speculative decoding (prompt-lookup drafting + batched multi-token
# verification). The verify ladder mirrors the decode-chunk ladder: one
# compiled k-token verify program per bucket, warmed at startup, the round's
# bucket chosen as the smallest covering the longest draft in the batch.
SPEC_VERIFY_BUCKETS = (2, 4, 8)

# In-loop device speculation (ISSUE 17): the fused while_loop's own n-gram
# drafter matches each lane's trailing 3/2-gram against a fixed window of
# its recent token history (carried ON DEVICE across loops) and verifies up
# to FUSED_SPEC_K drafted tokens as a batched branch of the same loop body —
# the lane never exits the loop to speculate. Window width trades match
# recall against per-iteration compare cost ([B, W, 3] equality — trivial
# next to a forward); 64 covers the tool-call/JSON span lengths the host
# drafter feeds on.
FUSED_HIST_W = 64
FUSED_SPEC_K = 4
# Dynamic fused rung: the loop bound is a RUNTIME operand, so one compiled
# executable serves every rung and the uncontended dispatch rides a rung
# this many times the configured decode_chunk — amortizing per-dispatch
# overhead (host bookkeeping, transfers, readback processing) that the
# b1/b4 decode-loop bench showed dominating fused ITL.
FUSED_RUNG_MULT = 4
# acceptance-rate EMA: fast-collapsing (a handful of all-rejected rounds
# sends gamma to 0) so adversarial/low-match traffic degrades to the plain
# decode ladder instead of paying verify forwards that never accept
SPEC_EMA_ALPHA = 0.4
SPEC_EMA_FLOOR = 0.125
# consecutive draft-lookup misses before a lane stops triggering the
# (pipeline-draining) speculation path; collapsed/missing lanes re-probe
# every SPEC_PROBE_EVERY decode steps so a workload shift is noticed
SPEC_MISS_BACKOFF = 4
SPEC_PROBE_EVERY = 32
# the drafter's reverse n-gram scan is pure Python on the worker thread,
# serialized inside the (synchronous) verify round: cap how far back it
# looks so a 4096-token context can't turn every lookup miss into
# milliseconds of host stall on the decode critical path
SPEC_LOOKUP_WINDOW = 1024


class SnapshotDeferred(Exception):
    """KV snapshot postponed: the engine is busy (or the global limiter is
    saturated) and durability is not yet overdue. Retry on a later turn."""


class EngineShutdown(RuntimeError):
    """The engine worker is gone; queued work can never complete. Raised
    into every abandoned future instead of letting callers hang forever."""


class RequestAborted(RuntimeError):
    """Base for per-request terminations that are POLICY, not faults: the
    request will never produce (more) tokens because nobody is waiting for
    them. Passed through to callers typed (like EngineShutdown) so the
    serve layer can map each to its HTTP status."""


class RequestExpired(RequestAborted):
    """Deadline passed before (or while) the request was served."""


class RequestCancelled(RequestAborted):
    """Explicit cancel(request_id) — client disconnected or operator abort."""


class EngineOverloaded(RuntimeError):
    """Submit-time shed: queue+waiting+active depth crossed the watermark.
    Raised synchronously from generate() BEFORE enqueueing, so overload
    backpressure costs the caller nothing but this exception. Carries a
    retry hint for the 429 Retry-After header."""

    def __init__(self, depth: int, watermark: int, retry_after_s: float = 1.0):
        super().__init__(f"engine overloaded: depth {depth} >= watermark {watermark}")
        self.depth = depth
        self.watermark = watermark
        self.retry_after_s = retry_after_s


class PagePoolExhausted(EngineOverloaded):
    """Paged-arena allocation failed even after evicting idle residents:
    the pool is genuinely full of in-flight + pinned pages. A POLICY
    backpressure signal, not a fault — subclasses EngineOverloaded so the
    serve layer maps it to 429 + Retry-After and the journal keeps the
    entry replayable (no acked loss)."""

    def __init__(self, need: int, free: int):
        super().__init__(depth=need, watermark=free)
        self.args = (
            f"KV page pool exhausted: need {need} page(s), {free} free",
        )


class TierPromoteFailed(EngineOverloaded):
    """Host-tier promotion failed (injected engine.kv_promote fault, or
    the pool couldn't fit the swap-in even after pressure demotion): the
    session STAYS parked — its context is preserved — and the triggering
    turn surfaces as 429 + Retry-After, so a retry finds the session
    still promotable. Subclasses EngineOverloaded for the same policy
    mapping as genuine pool exhaustion."""

    def __init__(self, session: str):
        super().__init__(depth=0, watermark=0)
        self.args = (f"KV tier promotion failed for session {session!r}",)


class EngineDraining(RuntimeError):
    """SIGTERM drain in progress: no new admissions; in-flight work is
    being finished and sessions snapshotted before exit."""


class PrefillFailed(RuntimeError):
    """Prefill broke for ONE request while the engine survived (the worker
    loop's per-request isolation). For a fixed prompt this is essentially
    deterministic — a poisoned input, not a transient — so the serve layer
    marks the 500 with PREFILL_POISON_HEADER and the proxy charges poison
    accounting (two strikes dead-letters the journal entry) instead of
    riding the full respawn/backoff ladder."""


def _as_prefill_failure(e: Exception) -> Exception:
    """Classify a prefill-tick exception: policy terminations pass through
    typed (they map to their own HTTP statuses); anything else becomes
    PrefillFailed."""
    if isinstance(e, (RequestAborted, EngineOverloaded, EngineShutdown)):
        return e
    return PrefillFailed(f"{type(e).__name__}: {e}")


def _sharded_random_init(cfg: ModelConfig, dtype, mesh, specs: dict) -> dict:
    """Random-init DIRECTLY into shards: ``jit(init, out_shardings=...)``
    makes every chip allocate only its own slice of every weight, so a
    meshed/pp engine whose model needs more than one chip's HBM never
    materializes the whole pytree on the default device first (VERDICT r3
    missing #3 — init-then-reshard OOMs chip 0 exactly when tp/pp matter).
    """
    from ..parallel.sharding import shardings_from_specs

    shardings = shardings_from_specs(mesh, specs)
    fn = jax.jit(lambda k: init_params(cfg, k, dtype=dtype), out_shardings=shardings)
    return fn(jax.random.PRNGKey(0))


@dataclass
class GenRequest:
    id: str
    session: str
    prompt_ids: list[int]
    max_tokens: int
    temperature: float
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future
    # absolute wall-clock give-up instant (None = no deadline): checked at
    # admission (fail fast before prefill) and per worker iteration while
    # in flight (park the lane, free the slot)
    deadline_at: float | None = None
    submitted_at: float = field(default_factory=time.monotonic)
    prefill_started_at: float | None = None
    # final prefill chunk + first-token injection dispatched; the tail of
    # TTFT after this instant is pure device/readback latency
    prefill_done_at: float | None = None
    ttft_ms: float | None = None
    # keep generating through EOS until max_tokens (benchmarks/load tests
    # that need a stream of fixed length; tiny random-weight models hit EOS
    # whenever argmax lands on it)
    ignore_eos: bool = False
    # nucleus/top-k filters, per request (0 / 1.0 = disabled): live in the
    # device carry as per-lane arrays so one compiled sampler serves a
    # batch mixing filtered and unfiltered lanes
    top_k: int = 0
    top_p: float = 1.0
    generated: list[int] = field(default_factory=list)
    # tokens sampled device-side so far (first token + dispatched decode
    # steps, including in-flight chunks): the remaining budget bounds how
    # large a decode chunk is worth dispatching
    dispatched: int = 0
    # SSE streaming: called from the worker thread as `emit(start, ids)`
    # right after tokens land in `generated` (start = offset of ids[0]).
    # Batches arrive FIFO and contiguous — the single worker thread is the
    # only appender. None (the default, and every buffered request) keeps
    # the readback paths byte-identical to pre-streaming behavior.
    emit: Any = None

    def emit_appended(self, n_new: int) -> None:
        """Report the last ``n_new`` tokens of ``generated`` to the emit
        callback (no-op without one). Never raises into the worker loop: a
        dead stream consumer must not fail the generation — the buffered
        result is still the journal's archive."""
        if self.emit is None or n_new <= 0:
            return
        try:
            self.emit(len(self.generated) - n_new, self.generated[-n_new:])
        except Exception:
            pass


@dataclass
class RestoreCmd:
    """Worker-queue command: write a KV snapshot into a slot (restores a
    session after an engine restart — BASELINE.json config #3)."""

    session: str
    k: Any  # np [L, pos, KV, hd]
    v: Any
    position: int
    pending_token: int | None
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future


@dataclass
class SnapshotCmd:
    """Worker-queue command: stage a session's KV prefix into fresh device
    buffers (fixed bucket shapes) for host serialization. Running on the
    worker thread makes it race-free against the donating decode/prefill
    dispatches — the staged output buffers are new arrays that survive any
    later donation of the cache itself (VERDICT r4 weak #2: a snapshot
    thread's captured cache reference was invalidated by the next decode)."""

    session: str
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future


@dataclass
class ParkCmd:
    """Worker-queue command: demote an idle session's KV off the device
    into the host RAM tier (kv_tiering). Resolves with the exact staged
    (k, v, position, pending_token) host arrays — the caller packs them
    into the store-durable SNAP_VERSION 3 blob (the cold tier) — or None
    when the session is unknown/busy or the demote failpoint fired."""

    session: str
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future


@dataclass
class PrewarmCmd:
    """Worker-queue command: promote a host-tier session back onto the
    device AHEAD of its next turn (the proxy's next-arrival hint), so the
    returning request admits against already-resident KV. Resolves True
    when the session is device-resident afterwards."""

    session: str
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future


@dataclass
class TieredEntry:
    """One parked session in the host RAM tier. ``k``/``v`` hold the
    position-trimmed KV prefix as host numpy — either the cache's exact
    dtype (tier_quantize=0) or int8 page tensors with per-page scales
    (``k_scale``/``v_scale``; 2–4x density at a bounded rounding cost).
    Self-speculation state parks with the KV so a promoted session drafts
    exactly like one that never left the device."""

    k: Any
    v: Any
    position: int
    pending_token: int | None
    nbytes: int
    parked_at: float
    quantized: bool = False
    k_scale: Any = None
    v_scale: Any = None
    pages: int = 0
    spec_hist: list[int] = field(default_factory=list)
    spec_ema: float = 1.0
    spec_miss: int = 0


@dataclass
class PrefixEntry:
    """One cached token-prefix in the prefix arena: the KV a prefill wrote
    for ``tokens`` (exact bucket length), held in fresh device buffers that
    outlive any later donation of the main cache. ``tokens`` is kept so a
    lookup verifies exact token equality — a rolling-hash collision must
    degrade to a miss, never serve another prompt's context."""

    k: Any  # [L, bucket, KV, hd], compute dtype (exact — no fp16 round-trip)
    v: Any
    tokens: tuple
    nbytes: int
    created: float
    last_used: float
    hits: int = 0
    # paged arena: instead of private k/v buffers the entry PINS pool
    # pages (refcounted, read-only) — zero-copy registration and forking.
    # A non-page-aligned level additionally owns one copied tail page
    # holding the partial last page (``tail_len`` live tokens).
    pages: list[int] | None = None
    tail_page: int | None = None
    tail_len: int = 0


@dataclass
class PagedSession:
    """A resident session in the paged arena: its KV lives in ``pages``
    (physical page ids, logical order), NOT in a lane — so a session
    between turns holds only its pages' HBM and zero compute lanes, and
    residency is bounded by the pool, not ``max_batch``. ``pages[:shared]``
    are refcount-shared prefix pages mapped read-only (the session never
    writes below its fork point, so sharing needs no guard beyond the
    partial-tail copy-on-write done at fork time)."""

    name: str
    pages: list[int] = field(default_factory=list)
    shared: int = 0
    position: int = 0
    pending_token: int | None = None
    # bound compute lane while a request is in flight; None between turns
    lane: int | None = None
    last_used: float = 0.0
    # admission-time pending token AND position, kept so a pool-exhaustion
    # failure can roll the session back to its pre-request state instead of
    # dropping it (position advances mid-request: the prefix map sets it at
    # admission and every speculative accept syncs it — neither belongs to
    # a request that ultimately failed with 429)
    admit_pending: int | None = None
    admit_position: int = 0
    admit_spec_hist: list[int] = field(default_factory=list)
    # self-speculation state persists across turns WITH the session (the
    # lane mirrors it while bound and syncs back at finish)
    spec_hist: list[int] = field(default_factory=list)
    spec_ema: float = 1.0
    spec_miss: int = 0


@dataclass
class Slot:
    idx: int
    session: str = ""
    position: int = 0  # next cache position to write
    # fresh-context prompts (prefill starting from position 0) are tracked
    # here so the final prefill chunk can register their bucket-prefixes in
    # the prefix arena; continuing sessions carry None (their context since
    # position 0 is not reconstructible from the request alone)
    prefix_ctx: list[int] | None = None
    request: GenRequest | None = None
    # prompt tokens not yet prefilled: chunked prefill feeds these through
    # the model a chunk at a time, interleaved with decode steps, so one
    # long prompt can't stall every active generation's ITL
    pending_prompt: list[int] = field(default_factory=list)
    last_used: float = 0.0
    # the final sampled token of the previous reply was never fed through the
    # model; it is prepended to the session's next prompt so the KV context
    # stays exact across turns
    pending_token: int | None = None
    # bumped whenever the slot is reassigned or its position resets; lets a
    # concurrent snapshot detect that its prefix went stale mid-serialize
    epoch: int = 0
    # host mirror of the DEVICE-side decode position for this slot's lane
    # (the pipelined decode chains positions on device; chunks already in
    # flight were dispatched at this offset)
    dev_position: int = 0
    # decoding = this slot's lane in the device carry is live (its first
    # token was injected and decode chunks are advancing it)
    decoding: bool = False
    # self-speculation state: the token stream fed through this slot's KV
    # across the session's turns (the drafter's lookup corpus), the
    # acceptance-rate EMA driving per-lane draft length, and lookup-miss /
    # probe bookkeeping bounding speculation's cost on low-match traffic
    spec_hist: list[int] = field(default_factory=list)
    spec_ema: float = 1.0
    spec_miss: int = 0
    spec_probe_at: int = -(10**9)
    # paged arena: the PagedSession bound to this lane while a request is
    # in flight (None in dense mode and between turns)
    psess: PagedSession | None = None


class LLMEngine:
    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        tokenizer,
        max_batch: int,
        max_seq: int,
        decode_chunk: int = 8,
        prefill_chunk: int = 256,
        tp: int = 1,
        ep: int = 1,
        sp: int = 1,
        pp: int = 1,
        devices: list | None = None,
        mesh=None,
        routed_moe: bool | None = None,
        moe_capacity_factor: float = 2.0,
        adaptive_decode: bool = True,
        prefix_cache: bool = True,
        prefix_cache_bytes: int = 0,
        deadlines: bool = True,
        shed_watermark: int = 0,
        speculative: bool = True,
        spec_gamma_max: int = 8,
        paged_kv: bool = False,
        page_size: int = PAGE_SIZE_DEFAULT,
        kv_pages: int = 0,
        fused_decode: bool = False,
        inloop_spec: bool = True,
        approx_topk: bool = False,
        kv_tiering: bool = False,
        tier_quantize: int = 1,
        streaming: bool = False,
    ):
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.pp = max(1, pp)
        self.sp = max(1, sp)
        # the sequence axis must split evenly over sp chips
        max_seq = ((max_seq + self.sp - 1) // self.sp) * self.sp
        # Paged KV arena (block tables): sessions hold lists of fixed-size
        # pages from a global pool instead of dense [max_seq] slots, so
        # resident sessions are bounded by the pool, prefix sharing maps
        # refcounted pages zero-copy, and speculative rewind truncates page
        # tails. paged_kv=False keeps the dense arena — the A/B baseline
        # (mirrors adaptive_decode / prefix_cache / speculative). sp stages
        # the SEQUENCE axis across chips and pp stages the cache over
        # layers with its own alloc path — neither composes with the page
        # pool yet, so they pin the dense arena.
        self.paged = bool(paged_kv) and self.sp == 1 and self.pp == 1
        if bool(paged_kv) and not self.paged:
            print(
                "[llm-engine] paged_kv disabled: not composable with "
                f"sp={self.sp}/pp={self.pp} yet (dense arena retained)",
                flush=True,
            )
        # Fused on-device decode loop: a per-ladder-rung compiled
        # lax.while_loop runs up to `chunk` forward+sample+append steps
        # entirely on device (per-lane EOS/budget masking, whole-batch
        # early exit) with ONE readback at loop exit — the per-chunk
        # host sync the ladder only shrank. fused_decode=False keeps the
        # per-chunk scan dispatch exactly as-is (the A/B baseline). pp
        # stages the forward across chips with host-side transfers per
        # step, which cannot live inside a device loop — pp pins unfused.
        self.fused_decode = bool(fused_decode) and self.pp == 1
        if bool(fused_decode) and not self.fused_decode:
            print(
                "[llm-engine] fused_decode disabled: not composable with "
                f"pp={self.pp} (per-chunk dispatch retained)",
                flush=True,
            )
        # Segmented approx top-k sampler (opt-in; exact shared-sort sampler
        # is the default). Static per engine: it picks which sample_step
        # pipeline every compiled decode path bakes in.
        self.approx_topk = bool(approx_topk)
        # SSE token streaming (opt-in): gates whether the serve layer
        # honors stream=true on /chat. The engine side is just the
        # per-request emit callback — sampling/batching are untouched, so
        # streaming=False keeps buffered behavior byte-identical.
        self.streaming = bool(streaming)
        self.page_size = max(8, int(page_size or PAGE_SIZE_DEFAULT))
        if self.paged:
            # the logical arena must tile exactly into pages
            max_seq = (
                (max_seq + self.page_size - 1) // self.page_size
            ) * self.page_size
        self.max_seq = max_seq
        # pages per full logical sequence (the block-table width)
        self._n_blocks = max(1, self.max_seq // self.page_size)
        # pool sizing: default matches the dense arena's HBM exactly
        # (max_batch × max_seq tokens of KV) so paged-vs-dense capacity is
        # an apples-to-apples A/B at unchanged budget; +max_batch dedicated
        # scratch pages (one per lane) absorb parked-lane and padding
        # writes without ever touching a session's pages
        self._data_pages = (
            max(1, int(kv_pages)) if kv_pages else max_batch * self._n_blocks
        )
        self._total_pages = self._data_pages + max_batch
        self.decode_chunk = max(1, decode_chunk)
        # Adaptive decode-chunk policy (admission-aware scheduling): a small
        # ladder of kernel-looped chunk sizes is compiled at warmup; the
        # dispatcher shrinks to the smallest bucket while anyone is waiting
        # for admission/prefill (the fixed chunk wall WAS the ~180 ms
        # admission half of single-chip TTFT) and reverts to the full chunk
        # at steady state so ITL/HBM efficiency is untouched.
        self.adaptive_decode = bool(adaptive_decode)
        if self.adaptive_decode:
            ladder = {self.decode_chunk}
            c = 1
            while c < self.decode_chunk:
                ladder.add(c)
                c *= 2
            self._decode_ladder = sorted(ladder)
        else:
            self._decode_ladder = [self.decode_chunk]
        # snap DOWN to a bucket: a non-bucket chunk size would pad every
        # non-final chunk up to the next bucket (wasted prefill compute)
        clamped = min(max(PREFILL_BUCKETS[0], prefill_chunk), PREFILL_BUCKETS[-1])
        self.prefill_chunk = max(b for b in PREFILL_BUCKETS if b <= clamped)
        self.tp = max(1, tp)
        self.ep = max(1, ep)
        # routed (token-dispatch) MoE is the default wherever experts shard
        # over ep — the dense path would burn ~E/k× the MLP FLOPs there
        # (VERDICT r3 missing #5); single-chip keeps the dense fallback
        # unless asked (options.routed)
        self.routed_moe = (
            cfg.is_moe and (self.ep > 1 if routed_moe is None else bool(routed_moe))
        )
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.scratch_pos = max_seq - 1  # idle-slot write target; never generated into
        dtype = params["final_norm"].dtype  # always dense, even when quantized
        if self.paged:
            # page pool [L, P, page_size, KV, hd]: same two-leaf pytree
            # discipline as the dense arena, so scan/donation/sharding
            # machinery applies unchanged
            cache_shape = (
                cfg.n_layers,
                self._total_pages,
                self.page_size,
                cfg.n_kv_heads,
                cfg.head_dim,
            )
        else:
            cache_shape = (cfg.n_layers, max_batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        self._pp_forward = None
        if self.pp > 1:
            # serve-time pipeline: layer stack AND the KV arena stage over
            # pp — each chip holds L/pp layers' weights plus L/pp of the
            # cache, so a model deeper than one chip's HBM serves at all
            # (parallel/pipeline.make_serve_pipeline_forward)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import make_mesh
            from ..parallel.pipeline import (
                make_serve_pipeline_forward,
                pipeline_param_specs,
            )

            # the mesh create() initialized params onto, when given — one
            # construction, so device_put below is a placement no-op rather
            # than a silent whole-model reshard if the two ever drifted
            self.mesh = mesh if mesh is not None else make_mesh(
                self.pp, pp=self.pp, devices=devices
            )
            p_sh = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                pipeline_param_specs(cfg.is_moe),
                is_leaf=lambda x: isinstance(x, P),
            )
            params = jax.device_put(params, p_sh)
            cache_sh = NamedSharding(self.mesh, P("pp", None, None, None, None))
            self._alloc_cache = jax.jit(
                lambda: KVCache(
                    jnp.zeros(cache_shape, dtype), jnp.zeros(cache_shape, dtype)
                ),
                out_shardings=KVCache(cache_sh, cache_sh),
            )
            cache = self._alloc_cache()
            self._pp_forward = make_serve_pipeline_forward(cfg, self.mesh)
        elif self.tp * self.ep * self.sp > 1:
            # serve-time model parallelism over the agent's ASSIGNED chips:
            # Megatron-style GSPMD shardings on a tp×ep mesh — heads/FFN
            # width split over tp, MoE expert weights split over ep (each
            # chip holds and computes E/ep experts; the top-k combine's
            # expert contraction becomes a psum — BASELINE config #5), KV
            # arena split on the kv-head axis; XLA inserts the ICI
            # collectives. (DP scale-out stays at the control plane via
            # `replicas: N`, matching the reference's fan-out.) Params
            # arrive host-side and are device_put directly with their
            # shardings, and the arena is allocated sharded, so nothing is
            # ever materialized whole on one chip.
            from jax.sharding import NamedSharding

            from ..parallel.mesh import make_mesh
            from ..parallel.sharding import cache_specs, param_shardings_for

            self.mesh = mesh if mesh is not None else make_mesh(
                self.tp * self.ep * self.sp,
                tp=self.tp,
                sp=self.sp,
                ep=self.ep,
                devices=devices,
            )
            # quant-aware: int8 QTensor leaves shard q on the dense spec and
            # replicate the scale across the contraction split
            params = jax.device_put(params, param_shardings_for(params, self.mesh, cfg.is_moe))
            if self.paged:
                # pool shards on the KV-head axis exactly like the dense
                # arena; the page axis stays whole (page ids are global —
                # the block-table gather must be shard-local, pinned by
                # tests/test_paged_hlo.py)
                from jax.sharding import PartitionSpec as _P

                cache_sh = NamedSharding(self.mesh, _P(None, None, None, "tp", None))
                self._alloc_cache = jax.jit(
                    lambda: PagedKVCache(
                        jnp.zeros(cache_shape, dtype), jnp.zeros(cache_shape, dtype)
                    ),
                    out_shardings=PagedKVCache(cache_sh, cache_sh),
                )
            else:
                cache_sh = NamedSharding(self.mesh, cache_specs(sp=self.sp > 1))
                self._alloc_cache = jax.jit(
                    lambda: KVCache(
                        jnp.zeros(cache_shape, dtype), jnp.zeros(cache_shape, dtype)
                    ),
                    out_shardings=KVCache(cache_sh, cache_sh),
                )
            cache = self._alloc_cache()
        else:
            self.mesh = None
            # single-chip: place on the ASSIGNED chip, not the default
            # device — on a multi-chip host two agents with different
            # single-chip slices must not both land on device 0. Explicit
            # device_put COMMITS the arrays: serve-time cache/carries are
            # jit outputs (always committed), and a committed-vs-not
            # mismatch is a different executable-cache key — warmup must
            # see the same placement real traffic will.
            dev = devices[0] if devices else jax.devices()[0]
            params = jax.device_put(params, dev)  # checkpoint loads arrive host-side

            if self.paged:

                def _alloc_single():
                    with jax.default_device(dev):
                        c = PagedKVCache.create(
                            cfg, self._total_pages, self.page_size, dtype=dtype
                        )
                    return jax.device_put(c, dev)

            else:

                def _alloc_single():
                    with jax.default_device(dev):
                        c = KVCache.create(cfg, max_batch, max_seq, dtype=dtype)
                    return jax.device_put(c, dev)

            self._alloc_cache = _alloc_single
            cache = self._alloc_cache()
        self.params = params
        self.cache = cache
        self.slots = [Slot(i) for i in range(max_batch)]
        # session membership surface. Dense: name → owning slot index (the
        # slot holds the KV). Paged: name → bound lane index while a
        # request is in flight, -1 while resident-but-idle — membership
        # and iteration keep working for the serve layer (restore checks,
        # drain snapshots), but the KV lives in paged_sessions[name].pages.
        self.sessions: dict[str, int] = {}
        # -- paged-arena allocator (host side; _page_lock guards it) ------
        # physical ids [0, _data_pages) are allocatable; ids [_data_pages,
        # _total_pages) are per-lane scratch pages (lane i owns id
        # _data_pages + i), permanently pinned, never shared: parked-lane
        # and bucket-padding writes land there instead of in any session's
        # pages. The authoritative block table is HOST state (numpy) and
        # ships to the device per dispatch — ~1 KB, async, and never a
        # recompile since it is an argument, not a constant.
        self.paged_sessions: dict[str, PagedSession] = {}
        self._page_lock = threading.RLock()
        self._page_free: list[int] = list(range(self._data_pages - 1, -1, -1))
        self._page_refs = np.zeros(self._total_pages, dtype=np.int64)
        # pages freed while readbacks are in flight park here: a chunk
        # dispatched BEFORE the free captured the old block table and will
        # still write into these pages — they must not be reallocated until
        # that dispatch's readback has drained
        self._page_quarantine: list[int] = []
        self._bt = np.empty((max_batch, self._n_blocks), dtype=np.int32)
        for i in range(max_batch):
            self._bt[i, :] = self._scratch_page(i)
        self.page_exhausted_total = 0
        self.pages_truncated = 0
        self.prefix_pages_shared = 0
        self._snap_paged_fns: dict[int, Any] = {}
        self._restore_paged_fns: dict[int, Any] = {}
        self._page_copy_fn_cached: Any = None

        # -- tiered KV hierarchy (device → pinned host RAM → store) -------
        # Idle sessions park their KV OFF the device: a host-tier entry
        # holds the position-trimmed prefix (exact dtype, or int8 with
        # per-page scales when tier_quantize is on), the device pages flow
        # back to the pool through the quarantine discipline, and the park
        # also yields an exact SNAP_VERSION 3 blob for the store (the cold
        # tier — survives the process). Promotion is the reverse and is
        # initiated from the admission path so the device swap-in overlaps
        # the queue-wait phase of TTFT. Works for BOTH arenas; the paged
        # pool additionally demotes under pressure before 429ing.
        self.kv_tiering = bool(kv_tiering)
        self.tier_quantize = int(tier_quantize)
        # _tier_lock guards _host_tier + byte/page gauges: API threads
        # insert (park) while the worker promotes/pressure-demotes. Never
        # held across device work or blocking readbacks.
        self._tier_lock = threading.Lock()
        self._host_tier: collections.OrderedDict[str, TieredEntry] = (
            collections.OrderedDict()
        )
        # host-RAM budget for parked KV: beyond it the LRU host entries are
        # dropped (their store blob remains — the cold tier serves the next
        # turn via the serve layer's restore-on-unknown path). Defaults to
        # one KV arena's worth of host RAM (stamped below, once the arena
        # byte count is known).
        self.tier_host_budget_bytes = 0
        self.tier_host_bytes = 0
        self.tier_quantized_pages = 0
        self.tier_demotions_total = 0
        self.tier_promotions_total = 0
        self.tier_pressure_demotions_total = 0
        self.tier_prewarm_hits_total = 0
        self.tier_demote_failures_total = 0
        self.tier_promote_failures_total = 0
        self.tier_host_evictions_total = 0
        self.tier_promote_overlap_ms_total = 0.0
        # promote-start instants by session, consumed when the promoted
        # session's next request dispatches its first prefill chunk — the
        # interval is restore latency HIDDEN behind the queue-wait phase
        self._tier_promote_started: dict[str, float] = {}
        self.tier_promote_overlap_ms_recent: collections.deque[float] = (
            collections.deque(maxlen=64)
        )

        # Device-side decode carry: the pipelined decode chains (token,
        # position, temperature) per slot lane ON DEVICE across chunks, so
        # steady-state decode never waits for a host round-trip (the axon
        # readback RTT measured ~24 ms — serial per chunk it dominated ITL).
        # Idle lanes park at scratch_pos exactly like the pre-pipeline
        # design; prefill injects a finished prompt's first token into its
        # lane with a jitted scatter instead of a host rebuild.
        def _mk_carry():
            return (
                jnp.zeros((max_batch,), jnp.int32),
                jnp.full((max_batch,), self.scratch_pos, jnp.int32),
                jnp.zeros((max_batch,), jnp.float32),
                jnp.zeros((max_batch,), jnp.int32),  # top_k (0 = disabled)
                jnp.ones((max_batch,), jnp.float32),  # top_p (1 = disabled)
                # in-loop spec history ring (right-aligned recent tokens)
                # + per-lane valid count; dead weight when the fused loop
                # or in-loop spec is off (W ints per lane — negligible),
                # kept in the carry unconditionally so every injection and
                # reallocation path has ONE shape.
                jnp.zeros((max_batch, FUSED_HIST_W), jnp.int32),
                jnp.zeros((max_batch,), jnp.int32),
            )

        if self.mesh is not None:
            from jax.sharding import NamedSharding as _NS, PartitionSpec as _P

            repl = _NS(self.mesh, _P())
            self._alloc_carry = jax.jit(
                _mk_carry, out_shardings=(repl,) * 7
            )
        else:
            # committed (see the cache comment above): first-use and
            # steady-state signatures must match
            self._alloc_carry = lambda: jax.device_put(_mk_carry(), dev)
        (
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            self._dhist,
            self._dhlen,
        ) = self._alloc_carry()
        # Double-buffered lane injection (ISSUE 17): a capacity-1 staging
        # slot a running fused loop absorbs at its next dispatch boundary.
        # The staged lane's (token, position, sampler params, spec history)
        # are scattered into these shadow arrays OUTSIDE the loop via the
        # same jitted _inject scatter the live carry uses; the next fused
        # dispatch ships a per-lane `armed` mask and the loop's entry merge
        # reads staged state for armed lanes — so a finished prefill starts
        # decoding WITHOUT the host waiting on the in-flight loop's
        # readback (exit-and-redispatch put that host RTT on the device's
        # idle path). _staged_lane tracks occupancy; an occupied slot falls
        # back to the direct-injection path (today's behavior).
        (
            self._stok,
            self._spos,
            self._stemps,
            self._stopk,
            self._stopp,
            self._shist,
            self._shlen,
        ) = self._alloc_carry()
        self._staged_lane: int | None = None
        # instance toggle (not a constructor flag quad: injection is a
        # fused-dispatch internal, A/B'd by tests flipping this directly)
        self._fused_inject = self.fused_decode
        self.fused_injections_total = 0
        self.fused_inject_fallbacks_total = 0
        # FIFO of lagged readbacks: ("first", slot, req, first_dev, t) and
        # ("chunk", [(slot, req, start_pos)...], toks_dev, t); staleness is
        # detected by `slot.request is not req` identity at processing time
        self._readbacks: collections.deque = collections.deque()

        self._queue: queue.Queue[GenRequest | None] = queue.Queue()
        # submitted-but-unadmitted items (burst drain / all slots busy);
        # worker-thread state, but an instance attribute so the dispatcher
        # can see contention and the shutdown path can fail what's left
        self._waiting: list = []
        self._sentinel = False  # shutdown marker observed by the worker
        self._completed: collections.OrderedDict[str, dict] = collections.OrderedDict()
        self._lock = threading.Lock()
        self._rng = jax.random.PRNGKey(0)
        self._running = True

        # counters
        self.tokens_generated = 0
        self.prefills = 0
        self.ttft_ms_recent: collections.deque[float] = collections.deque(maxlen=256)
        self.itl_ms_recent: collections.deque[float] = collections.deque(maxlen=256)
        # TTFT phase decomposition: queue-wait (admission → first prefill
        # chunk dispatched), prefill (first chunk → first-token injection),
        # first-readback (injection → token on host). The phases regress
        # independently — admission is scheduler policy, the rest is device
        # work — so they are tracked independently (VERDICT r4 #10, r5 #3).
        self.admission_ms_recent: collections.deque[float] = collections.deque(maxlen=256)
        self.prefill_ms_recent: collections.deque[float] = collections.deque(maxlen=256)
        self.first_readback_ms_recent: collections.deque[float] = collections.deque(
            maxlen=256
        )
        # adaptive-chunk observability: dispatched chunk-size histogram and
        # how often contention shrank below the configured chunk
        self.decode_chunk_hist: dict[int, int] = {}
        self.decode_chunks_shrunk = 0
        self.worker_errors = 0
        self.last_worker_error = ""
        self.cache_resets = 0
        # End-to-end deadline plumbing (deadlines=False is the A/B baseline:
        # no expiry checks, no overload shed — exactly the prior behavior;
        # explicit cancel() still works, it is an API, not policy).
        self.deadlines = bool(deadlines)
        # submit-time shed watermark on queue+waiting+active depth; 0 = off
        # (the historical unbounded queue). The serve layer maps the raised
        # EngineOverloaded to 429 + Retry-After.
        self.shed_watermark = max(0, int(shed_watermark))
        # request-id → cancel-record time (guarded by self._lock). TTL'd:
        # a cancel for an id the engine never ends up seeing (client died
        # before its dispatch arrived) must not poison a LATER legitimate
        # dispatch of the same id (operator requeue) nor accumulate forever.
        self._cancel_requested: dict[str, float] = {}
        self._cancel_ttl_s = 30.0
        self._draining = False
        self.cancelled_total = 0
        self.expired_total = 0
        self.shed_total = 0
        self._snap_fns: dict[int, Any] = {}
        # global limiter: one snapshot staging per gap — the readback rides
        # the same device stream decode lives on (a bucket-128 8B snapshot
        # measured ~1.25s of tunnel readback), so unthrottled snapshots
        # from many sessions at once would tax every in-flight generation
        self.snapshot_min_gap_s = 2.0
        # busy engines defer snapshots to idle moments, but never longer
        # than this per session (durability floor under sustained load)
        self.snapshot_force_s = 30.0
        # minimum spacing between stagings while OTHER requests decode
        self.snapshot_busy_gap_s = 10.0
        # gap-free first snapshot, but the force timer starts fresh
        self._last_snapshot_at = time.monotonic() - self.snapshot_min_gap_s
        # session → SnapshotCmd parked until the session's request settles
        self._snap_parked: dict[str, SnapshotCmd] = {}
        # per-session staging times for the durability floor (bounded: one
        # entry per session name ever snapshotted; evictions clean up)
        self._snap_last_by_session: dict[str, float] = {}
        self._snap_epoch0 = time.monotonic()
        self._prefilling_slot: Slot | None = None
        # HBM traffic model for MBU (decode is memory-bound; MFU alone
        # judges it against the wrong roofline — VERDICT r4 item 6): every
        # decode step streams the weights once plus each active lane's KV
        # prefix; prefill streams the weights once per chunk.
        self.hbm_bytes_read = 0.0
        self._kv_bytes_per_pos = (
            2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * cache.k.dtype.itemsize
        )
        self.decode_steps = 0
        self._occupancy_sum = 0.0
        self._last_decode_end: float | None = None
        self._started_at = time.monotonic()

        # FLOP/HBM accounting (VERDICT r2 items 1-2/10): achieved model
        # FLOPs accumulate per prefill chunk / decode token so the metrics
        # plane can report MFU against the spanned chips' spec-sheet peak;
        # weight/arena bytes let the scheduler's HBM claims be audited.
        from ..utils.hw import chip_spec

        self.flops_done = 0.0
        self.param_hbm_bytes = sum(
            x.nbytes for x in jax.tree.leaves(params)
        )
        self.kv_arena_bytes = cache.k.nbytes + cache.v.nbytes
        if not self.tier_host_budget_bytes:
            self.tier_host_budget_bytes = self.kv_arena_bytes
        # Cross-session prefix arena: bucket-length token prefixes → their
        # prefilled KV, populated the first time a prefix is prefilled and
        # forked into a fresh slot on admission (the second session with a
        # shared system prompt prefills only its uncached tail). Keyed by a
        # rolling hash of the token ids at bucket granularity, verified by
        # exact token equality, LRU-evicted under the bytes budget.
        # prefix_cache=False is the A/B baseline (mirrors adaptive_decode).
        self.prefix_cache = bool(prefix_cache)
        self._prefix_active = self.prefix_cache  # warmup serves with it off
        # bucket levels a prefix can be cached at: a hit must leave ≥1
        # prompt token to prefill (the first token is sampled from prefill
        # logits), so levels cap below the longest admissible prompt
        self._prefix_levels = [b for b in PREFILL_BUCKETS if b <= max_seq - 2]
        self._prefix_entries: collections.OrderedDict[tuple, PrefixEntry] = (
            collections.OrderedDict()
        )
        self._prefix_bytes = 0
        # arena budget defaults to the main KV arena's size: one extra
        # arena's worth of HBM buys ~every repeat prefill in the workload.
        # Paged engines pin prefix pages INSIDE the pool (no extra HBM), so
        # the default caps pinning at half the pool — the other half stays
        # for live sessions; pool pressure can still evict pinned entries.
        if prefix_cache_bytes:
            self._prefix_budget = int(prefix_cache_bytes)
        elif self.paged:
            self._prefix_budget = self.kv_arena_bytes // 2
        else:
            self._prefix_budget = self.kv_arena_bytes
        self._prefix_slice_fns: dict[int, Any] = {}
        self._prefix_fork_fns: dict[int, Any] = {}
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0
        # eviction observability (session KV eviction used to be silent):
        # both the slot LRU and the prefix arena count through the same
        # path, so hit-rate regressions trace to churn in either pool
        self.session_evictions = 0
        self.prefix_evictions = 0
        self.session_eviction_idle_s_recent: collections.deque[float] = (
            collections.deque(maxlen=64)
        )
        self.prefix_eviction_idle_s_recent: collections.deque[float] = (
            collections.deque(maxlen=64)
        )
        # Self-speculative decoding (prompt-lookup drafting + batched
        # multi-token verification): a host-side drafter matches each
        # slot's trailing n-gram against its own token stream and proposes
        # up to gamma continuation tokens; one compiled verify forward per
        # round scores every lane's drafts in parallel and accepts the
        # longest agreeing prefix. speculative=False is the A/B baseline
        # (mirrors adaptive_decode / prefix_cache).
        self.speculative = bool(speculative)
        gamma_max = max(1, min(int(spec_gamma_max), SPEC_VERIFY_BUCKETS[-1]))
        self._spec_buckets = [
            b for b in SPEC_VERIFY_BUCKETS if b <= gamma_max
        ] or [SPEC_VERIFY_BUCKETS[0]]
        # snap DOWN to the largest compiled bucket: a gamma between buckets
        # (e.g. 5 with ladder {2,4}) would draft longer than any verify
        # program covers and the round's bucket pick would fail
        self.spec_gamma_max = self._spec_buckets[-1]
        self._verify_fns: dict[int, Any] = {}
        # In-loop device speculation: the fused loop drafts and verifies on
        # device, so speculating lanes stay loop-resident (the host-side
        # drafter forces a loop exit + synchronous verify round-trip every
        # round). Requires the fused loop and the speculative flag; meshed
        # engines keep the host drafter — the draft/verify lax.cond inside
        # the loop body trips the same XLA:CPU partitioner segfault the
        # sampler's greedy cond does over sharded operands.
        self.inloop_spec = (
            bool(inloop_spec)
            and self.fused_decode
            and bool(speculative)
            and self.mesh is None
        )
        self.inloop_spec_drafted = 0
        self.inloop_spec_accepted = 0
        self._spec_active = self.speculative  # warmup serves with it off
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        self.spec_verify_hist: dict[int, int] = {}
        # fused-loop observability (ISSUE 10): loops dispatched, on-device
        # steps actually executed (early exits run fewer than the rung),
        # loops that exited before the rung bound, exit-reason histogram,
        # and host syncs — every host materialization of device decode
        # output bumps host_syncs_total, so syncs/token quantifies the
        # one-readback-per-loop claim against the per-chunk baseline.
        self._fused_fns: dict[int, Any] = {}
        # dynamic-rung cap: the single compiled loop's static sizing bound
        # (emitted buffer, key ladder); the runtime loop bound `nsteps` is
        # an operand, so dispatch picks any rung in [1, cap] at zero
        # compile cost and the uncontended steady state rides the top
        self._fused_cap = max(self.decode_chunk, FUSED_RUNG_MULT * self.decode_chunk)
        self.fused_loops_total = 0
        self.fused_steps_total = 0
        self.fused_early_exits_total = 0
        self.fused_exit_reason_hist: dict[str, int] = {}
        self.host_syncs_total = 0
        self._n_chips = self.tp * self.ep * self.sp * self.pp
        self._chip = chip_spec((devices or jax.devices() or [None])[0])
        self._peak_flops = self._chip.bf16_flops * self._n_chips
        self._peak_hbm_bps = self._chip.hbm_gbps * self._n_chips

        self._build_compiled()
        self._worker = threading.Thread(target=self._loop, daemon=True, name="llm-engine")
        self._worker.start()

    # -- construction -----------------------------------------------------
    @classmethod
    def create(
        cls,
        config_name: str,
        checkpoint: str = "",
        agent_id: str = "",
        store=None,
        options: dict | None = None,
    ) -> "LLMEngine":
        options = options or {}
        # HF checkpoints carry their own config.json — derive the config
        # from the checkpoint itself so a mistyped/missing config name can't
        # cause an opaque shape error deep in the loader (ADVICE round-1)
        from .hf_convert import config_from_hf, is_hf_checkpoint

        if checkpoint and is_hf_checkpoint(checkpoint):
            try:
                cfg = config_from_hf(checkpoint)
            except (OSError, KeyError, ValueError) as e:
                # converted weights without a (llama-style) config.json: an
                # explicit config name remains authoritative
                if not config_name:
                    raise ValueError(
                        f"checkpoint {checkpoint!r} has no usable config.json "
                        f"({e!r}); pass model.config explicitly"
                    ) from e
                cfg = get_config(config_name)
        else:
            cfg = get_config(config_name or "tiny")
        tokenizer = load_tokenizer(cfg.vocab_size, checkpoint)
        dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        quant = str(options.get("quant", "") or "").lower()
        if quant and quant != "int8":
            raise ValueError(f"unknown quant scheme {quant!r} (supported: int8)")

        # serve-time TP: the control plane passes the agent's assigned chip
        # ids (llm_serve); clamp to the visible devices and to a divisor of
        # the model's head counts. Standalone default is single-chip.
        # int8 quant keeps TP: the QTensor pytree gets matching shardings
        # (parallel/sharding.param_shardings_for).
        from ..parallel.mesh import pick_ep, pick_tp

        all_devices = jax.devices()
        chips = [int(c) for c in options.get("chips", []) or []]
        tp_asked = int(options.get("tp", 0) or 0)
        ep_asked = int(options.get("ep", 0) or 0)
        sp_asked = int(options.get("sp", 0) or 0)
        pp_asked = int(options.get("pp", 0) or 0)
        # chip budget: an explicit chip assignment is the placement
        # authority — tp×sp×ep may only narrow the span, never spill onto
        # chips owned by other agents; standalone (no assignment) spans
        # exactly what the options ask for
        if chips:
            budget = min(len(chips), len(all_devices))
        else:
            budget = min(
                len(all_devices),
                max(1, tp_asked) * max(1, ep_asked) * max(1, sp_asked) * max(1, pp_asked),
            )
        if pp_asked > 1:
            # serve-time pipeline: layers + arena staged over pp (v0
            # composes with nothing else — one axis, whole assignment)
            if tp_asked or ep_asked or sp_asked:
                raise ValueError("serve-time pp does not compose with tp/ep/sp yet")
            if quant:
                raise ValueError("serve-time pp does not support quantized weights yet")
            if options.get("routed"):
                raise ValueError("serve-time pp does not support routed MoE yet")
            pp = min(pp_asked, budget)
            if cfg.n_layers % pp or cfg.vocab_size % pp:
                raise ValueError(
                    f"pp={pp} must divide n_layers={cfg.n_layers} and "
                    f"vocab={cfg.vocab_size}"
                )
            if chips and len(chips) >= pp and all(c < len(all_devices) for c in chips):
                devices = [all_devices[c] for c in chips[:pp]]
            else:
                devices = list(all_devices[:pp])
            from ..parallel.mesh import make_mesh as _mk

            mesh = _mk(pp, pp=pp, devices=devices)
            if checkpoint:
                # deploy serves what you named (agent.go:104-142): pp
                # engines load the checkpoint host-side; __init__'s
                # device_put places each stage's slice straight onto its
                # chip (VERDICT r3 missing #2 — this branch used to serve
                # random weights silently)
                from .checkpoint import load_params

                params = load_params(cfg, checkpoint, dtype=dtype)
            else:
                from ..parallel.pipeline import pipeline_param_specs as _pps

                params = _sharded_random_init(cfg, dtype, mesh, _pps(cfg.is_moe))
            engine = cls(
                cfg,
                params,
                tokenizer,
                max_batch=int(options.get("max_batch", 8)),
                max_seq=int(options.get("max_seq", min(cfg.max_seq_len, 2048))),
                decode_chunk=int(options.get("decode_chunk", 8)),
                prefill_chunk=int(options.get("prefill_chunk", 256)),
                pp=pp,
                devices=devices,
                mesh=mesh,
                adaptive_decode=bool(options.get("adaptive_decode", True)),
                prefix_cache=bool(options.get("prefix_cache", True)),
                prefix_cache_bytes=int(options.get("prefix_cache_bytes", 0) or 0),
                deadlines=bool(options.get("deadlines", True)),
                shed_watermark=int(options.get("shed_watermark", 0) or 0),
                speculative=bool(options.get("speculative", True)),
                spec_gamma_max=int(options.get("spec_gamma_max", 8) or 8),
                paged_kv=bool(options.get("paged_kv", False)),
                page_size=int(options.get("page_size", PAGE_SIZE_DEFAULT) or PAGE_SIZE_DEFAULT),
                kv_pages=int(options.get("kv_pages", 0) or 0),
                fused_decode=bool(options.get("fused_decode", False)),
                inloop_spec=bool(options.get("inloop_spec", True)),
                approx_topk=bool(options.get("approx_topk", False)),
                kv_tiering=bool(options.get("kv_tiering", False)),
                tier_quantize=int(options.get("tier_quantize", 1) or 0),
                streaming=bool(options.get("streaming", False)),
            )
            if not options.get("skip_warmup"):
                engine.warmup()
            return engine
        # sequence parallelism is opt-in (long-context serving); requested
        # sp reserves its chips before the tp/ep split
        model_budget = max(1, budget // max(1, sp_asked))
        if cfg.is_moe:
            # EP-first: experts dominate a MoE model's HBM footprint, and
            # "Mixtral across the slice via EP" is the flagship scale-out
            # config. Explicit tp/ep options override the split.
            if ep_asked:
                ep = pick_ep(cfg, min(ep_asked, model_budget))
                tp = pick_tp(cfg, min(max(1, tp_asked), model_budget // ep))
            elif tp_asked:
                tp = pick_tp(cfg, min(tp_asked, model_budget))
                ep = pick_ep(cfg, model_budget // tp)
            else:
                ep = pick_ep(cfg, model_budget)
                tp = pick_tp(cfg, model_budget // ep)
        else:
            ep = 1
            # dense + assigned chips + no explicit tp: span the whole
            # assignment (the scheduler sized it; idle chips help nobody)
            dense_tp = tp_asked if tp_asked else (model_budget if chips else 1)
            tp = pick_tp(cfg, min(max(1, dense_tp), model_budget))
        sp = max(1, min(sp_asked, budget // (tp * ep))) if sp_asked else 1
        n_use = tp * ep * sp
        asked = max(1, tp_asked) * max(1, ep_asked) * max(1, sp_asked)
        if n_use < min(asked, budget) or (chips and n_use < len(chips)):
            print(
                f"[llm-engine] parallelism narrowed to tp={tp} ep={ep} sp={sp} "
                f"(asked tp={tp_asked or 'auto'} ep={ep_asked or 'auto'} "
                f"sp={sp_asked or 'auto'}, "
                f"assigned chips={len(chips) or 'none'}, visible devices="
                f"{len(all_devices)}, model kv_heads={cfg.n_kv_heads}, "
                f"heads={cfg.n_heads}, experts={cfg.n_experts}); "
                "extra chips idle",
                flush=True,
            )
        # the mesh spans the ASSIGNED chips when their ids map to visible
        # devices (multi-chip host); engines on a tunneled/virtual platform
        # fall back to the first tp*ep devices
        if chips and len(chips) >= n_use and all(c < len(all_devices) for c in chips):
            devices = [all_devices[c] for c in chips[:n_use]]
        else:
            devices = list(all_devices[:n_use])

        mesh = None
        if n_use > 1:
            from ..parallel.mesh import make_mesh as _mk

            mesh = _mk(n_use, tp=tp, sp=sp, ep=ep, devices=devices)
        synthetic = bool(options.get("synthetic"))
        if checkpoint:
            from .checkpoint import load_params

            params = load_params(cfg, checkpoint, dtype=dtype)  # host-side
        elif synthetic and quant:
            # benchmark-grade int8 weights generated directly in HBM: no
            # minutes-long host init, no multi-GB host→device transfer.
            # Meshed engines generate each leaf WITH its sharding, so every
            # chip allocates only its slice (VERDICT r3 missing #3).
            from .quant import synthetic_quantized_params

            if mesh is not None:
                params = synthetic_quantized_params(cfg, dtype, mesh=mesh)
            else:
                params = synthetic_quantized_params(
                    cfg, dtype, device=devices[0] if devices else None
                )
        elif quant:
            # random init on the HOST when quantizing: the dense bf16 model
            # may be exactly what doesn't fit the chip
            try:
                cpu0 = jax.local_devices(backend="cpu")[0]
            except Exception:
                cpu0 = None
            if cpu0 is not None:
                with jax.default_device(cpu0):
                    params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
            else:
                params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        elif mesh is not None:
            # meshed random init allocates straight into shards — never the
            # whole model on the default device (VERDICT r3 missing #3)
            from ..parallel.sharding import param_specs as _ps

            params = _sharded_random_init(cfg, dtype, mesh, _ps(cfg.is_moe))
        else:
            params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        if quant and not (synthetic and not checkpoint):
            from .quant import quantize_params

            # host-side: only the int8 model ever reaches HBM (synthetic
            # init already produced QTensors in device memory)
            params = quantize_params(params, dtype)
        max_batch = int(options.get("max_batch", 8))
        # long-context default scales with sp: the sharded arena holds
        # sp× one chip's context budget (explicit max_seq still wins)
        max_seq = int(options.get("max_seq", min(cfg.max_seq_len, 2048 * sp)))
        decode_chunk = int(options.get("decode_chunk", 8))
        prefill_chunk = int(options.get("prefill_chunk", 256))
        engine = cls(
            cfg,
            params,
            tokenizer,
            max_batch=max_batch,
            max_seq=max_seq,
            decode_chunk=decode_chunk,
            prefill_chunk=prefill_chunk,
            tp=tp,
            ep=ep,
            sp=sp,
            devices=devices,
            mesh=mesh,
            routed_moe=options.get("routed"),
            moe_capacity_factor=float(options.get("moe_cf", 2.0)),
            adaptive_decode=bool(options.get("adaptive_decode", True)),
            prefix_cache=bool(options.get("prefix_cache", True)),
            prefix_cache_bytes=int(options.get("prefix_cache_bytes", 0) or 0),
            deadlines=bool(options.get("deadlines", True)),
            shed_watermark=int(options.get("shed_watermark", 0) or 0),
            speculative=bool(options.get("speculative", True)),
            spec_gamma_max=int(options.get("spec_gamma_max", 8) or 8),
            paged_kv=bool(options.get("paged_kv", False)),
            page_size=int(options.get("page_size", PAGE_SIZE_DEFAULT) or PAGE_SIZE_DEFAULT),
            kv_pages=int(options.get("kv_pages", 0) or 0),
            fused_decode=bool(options.get("fused_decode", False)),
            inloop_spec=bool(options.get("inloop_spec", True)),
            approx_topk=bool(options.get("approx_topk", False)),
            kv_tiering=bool(options.get("kv_tiering", False)),
            tier_quantize=int(options.get("tier_quantize", 1) or 0),
            streaming=bool(options.get("streaming", False)),
        )
        # pay the decode/prefill compiles here (inside the loader thread, while
        # /health keeps answering) instead of on the first user request.
        # skip_warmup (set on engine RESPAWN when the persistent XLA cache is
        # already populated) trades a few cache-load hiccups on the first
        # requests for a much shorter crash-recovery time — the compiles are
        # disk loads, not recompiles.
        if not options.get("skip_warmup"):
            engine.warmup()
        return engine

    def _build_compiled(self) -> None:
        cfg = self.cfg
        use_flash = self.mesh is None
        # Meshed engines can't let GSPMD partition a pallas_call, but
        # attention is embarrassingly parallel over heads/batch — so tp/ep
        # engines run the SAME flash kernels per device inside a shard_map
        # body (parallel/flash_mesh.py). sp-sharded arenas stay on the
        # einsum path (they need the partial-softmax combine XLA derives).
        cache_attn_impl = None
        if self.mesh is not None and self.sp == 1 and self.pp == 1 and not self.paged:
            from ..parallel.flash_mesh import make_meshed_cache_attention, resolve_mesh_flash

            interp = resolve_mesh_flash(cfg, self.tp)
            if interp is not None:
                cache_attn_impl = make_meshed_cache_attention(self.mesh, interpret=interp)
        self.meshed_flash = cache_attn_impl is not None

        moe_impl = None
        if self.routed_moe and self.pp == 1:
            if self.mesh is not None and self.ep > 1:
                from ..parallel.expert import make_routed_moe

                moe_impl = make_routed_moe(
                    self.mesh, cfg, capacity_factor=self.moe_capacity_factor
                )
            else:
                from functools import partial as _partial

                from ..models.llama import _moe_mlp_routed

                moe_impl = _partial(
                    _moe_mlp_routed,
                    cfg=cfg,
                    capacity_factor=self.moe_capacity_factor,
                )
        self.routed_moe = moe_impl is not None

        pp_forward = self._pp_forward

        def run_forward(params, toks, pos, cache, bt=None):
            if pp_forward is not None:
                logits, k, v = pp_forward(params, toks, pos, cache.k, cache.v)
                return logits, KVCache(k, v)
            return forward(
                params,
                cfg,
                toks,
                pos,
                cache,
                use_flash=use_flash,
                cache_attn_impl=cache_attn_impl,
                moe_impl=moe_impl,
                block_table=bt,
            )

        # the paged fns can't read the logical arena length off the cache
        # (its page axis is pool-wide); close over it statically
        scratch_static = self.max_seq - 1

        def prefill(params, cache, slot, tokens, positions, n_real):
            # slice the slot's cache row, run the prompt, write the row back
            rowk = lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1)
            rowv = lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1)
            logits, row = run_forward(params, tokens, positions, KVCache(rowk, rowv))
            newk = lax.dynamic_update_slice_in_dim(cache.k, row.k, slot, axis=1)
            newv = lax.dynamic_update_slice_in_dim(cache.v, row.v, slot, axis=1)
            last = lax.dynamic_slice_in_dim(logits, n_real - 1, 1, axis=1)[0, 0]
            return last, KVCache(newk, newv)

        def prefill_paged(params, cache, bt, tokens, positions, n_real):
            # no row slice/write-back: the lane's single-row block table IS
            # the view, and writes land in pool pages directly
            logits, cache = run_forward(params, tokens, positions, cache, bt)
            last = lax.dynamic_slice_in_dim(logits, n_real - 1, 1, axis=1)[0, 0]
            return last, cache

        def decode_n(params, cache, tokens, positions, temps, topk, topp, keys, bt=None):
            """Kernel-looped decode: ``chunk`` autoregressive steps inside one
            compiled call (lax.scan), so the host↔device round trip is paid
            once per chunk, not once per token. The (token, position) carry
            is returned so the NEXT chunk can chain on it device-side — the
            worker never has to wait for tokens to cross the host boundary
            between chunks. Tokens a request doesn't end up using are rolled
            back by the worker (their cache writes are overwritten before any
            later query can attend to them). One body serves both arenas:
            with ``bt`` the cache is the page pool (block table constant
            across the chunk — the dispatcher pre-allocates every step's
            pages) and the scratch clamp comes from the engine statics,
            since the pool's page axis says nothing about logical length."""

            scratch = cache.k.shape[2] - 1 if bt is None else scratch_static

            def step(carry, key):
                tok, pos, cache = carry
                logits, cache = run_forward(params, tok[:, None], pos[:, None], cache, bt)
                nxt = sample_step(
                    logits[:, 0], key, temps, topk, topp,
                    greedy_cond=self.mesh is None,
                    approx_topk=self.approx_topk,
                )
                # clamp: parked (idle/finished) lanes decode forever at the
                # scratch position — real lanes never reach it (admission
                # budgets position + max_tokens below it)
                return (nxt, jnp.minimum(pos + 1, scratch), cache), nxt

            (tok, pos, cache), toks = lax.scan(step, (tokens, positions, cache), keys)
            return toks, tok, pos, cache  # toks [chunk, B]

        def decode_n_paged(params, cache, bt, tokens, positions, temps, topk, topp, keys):
            # positional-arg adapter for the call-site splat (bt sits
            # between cache and the token state); the body is decode_n
            return decode_n(params, cache, tokens, positions, temps, topk, topp, keys, bt)

        def inject(
            tok, pos, temps, topk, topp, hist, hlen,
            idx, first, position, temp, tk, tp_, hist_row, hist_n,
        ):
            """Point a slot's decode lane at its prefill result: lane `idx`
            continues from `first` (the sampled first token, still on
            device) at `position`. Idle/finished lanes are parked the same
            way with first=0, position=scratch. The in-loop spec history is
            seeded in the same scatter: ``hist_row`` carries the host-built
            prompt tail shifted left one slot, and ``first`` (still a
            device value) lands in the newest slot — so the drafter's first
            trailing gram already includes the first generated token."""
            row = jnp.concatenate([hist_row[1:], first[None].astype(jnp.int32)])
            return (
                tok.at[idx].set(first),
                pos.at[idx].set(position),
                temps.at[idx].set(temp),
                topk.at[idx].set(tk),
                topp.at[idx].set(tp_),
                hist.at[idx].set(row),
                hlen.at[idx].set(hist_n),
            )

        if self.paged:
            self._prefill = jax.jit(prefill_paged, donate_argnums=(1,))
            self._decode_n = jax.jit(decode_n_paged, donate_argnums=(1, 3, 4))
        else:
            self._prefill = jax.jit(prefill, donate_argnums=(1,))
            self._decode_n = jax.jit(decode_n, donate_argnums=(1, 2, 3))
        self._inject = jax.jit(inject, donate_argnums=(0, 1, 2, 3, 4, 5, 6))
        # the verify ladder reuses the same forward (one prefill-shaped call
        # with t = k+1 per round); fns are built per bucket on demand and
        # warmed alongside the decode ladder
        self._run_forward = run_forward

    def _fused_fn(self):
        """Compiled fused decode loop (ISSUE 10, reworked for ISSUE 17): a
        ``lax.while_loop`` running up to ``nsteps`` iterations entirely on
        device, with per-lane EOS masking, in-loop n-gram speculation, a
        double-buffered injection slot, and a whole-batch early-exit
        predicate — the only host↔device traffic per loop is the dispatch
        and ONE packed readback at loop exit.

        Dynamic rung: ``nsteps`` is a RUNTIME int32 operand; buffers are
        sized by the static cap ``self._fused_cap``, so ONE executable
        serves every rung of the adaptive ladder (recompile budget stays 0
        by construction) and long uncontended rungs amortize dispatch
        overhead without new compiles.

        Injection slot: ``armed`` flags lanes whose staged shadow state
        (stok/spos/... written by ``_stage_lane`` while the previous loop
        was in flight) replaces the carry at entry — a freshly prefilled
        request is absorbed by the already-pipelined next loop without an
        exit-and-redispatch bubble.

        In-loop speculation (greedy lanes only): each iteration drafts up
        to ``FUSED_SPEC_K`` tokens by matching the lane's trailing 3-gram
        (2-gram fallback) against its ``FUSED_HIST_W``-token history
        carry, then verifies the drafts as a batched [B, K+1] forward in a
        ``lax.cond`` branch of the SAME loop body. Acceptance is argmax
        agreement, so greedy lanes stay bit-exact with both
        ``speculative=False`` and the host-side drafter; sampled lanes
        never draft (dlen=0) and consume exactly ``keys[i]`` per
        iteration, so their streams are identical too.

        Budget handling: ``budgets`` is a per-loop emission cap
        (min(remaining, chunk+1) estimated by the host). The device NEVER
        declares a budget finish — a lane hitting its cap freezes
        (``full``: real tok/pos retained, reason stays 0, excluded from
        the active set) and the authoritative host rescan in
        ``_process_fused`` decides. Host dispatch counts iterations, not
        emissions, so the estimate only ever OVERSHOOTS remaining budget —
        the safe direction under pipelined dispatch (a device park the
        host disagrees with would let the in-flight next loop decode a
        host-live lane at scratch).

        Readback packing: one int32 [cap+6, B] array — rows [0, cap+1)
        emitted tokens (-1 past a lane's count), then per-lane counts,
        finish reasons (0 running / 1 EOS), executed iteration count
        (broadcast), accepted-draft and drafted counts."""
        fn = self._fused_fns.get(self._fused_cap)
        if fn is not None:
            return fn
        run_forward = self._run_forward
        scratch_static = self.max_seq - 1
        eos_id = int(self.tokenizer.eos_id)
        cap_rows = self._fused_cap + 1  # budgets clamp at chunk+1 emissions
        K = FUSED_SPEC_K
        W = FUSED_HIST_W
        inloop_spec = self.inloop_spec
        approx = self.approx_topk
        greedy_cond = self.mesh is None
        # Static index matrices for the n-gram drafter: row d-1 of idx3
        # addresses the 3-token window at distance d back from the trailing
        # 3-gram (d in 1..W-3); first match = smallest d via argmax.
        d3_vals = jnp.arange(1, W - 2, dtype=jnp.int32)
        idx3 = (W - 3 - d3_vals)[:, None] + jnp.arange(3)[None, :]
        d2_vals = jnp.arange(1, W - 1, dtype=jnp.int32)
        idx2 = (W - 2 - d2_vals)[:, None] + jnp.arange(2)[None, :]

        def fused_body(  # atp: hot
            params, cache, tok, pos, temps, topk, topp, hist, hlen,
            stok, spos, stemps, stopk, stopp, shist, shlen,
            armed, live, budgets, ign, keys, nsteps, bt=None,
        ):
            scratch = cache.k.shape[2] - 1 if bt is None else scratch_static
            B = tok.shape[0]
            # Absorb the staged lane (if armed) at loop entry — the shadow
            # state was written while the previous loop was in flight.
            tok = jnp.where(armed, stok, tok)
            pos = jnp.where(armed, spos, pos)
            temps = jnp.where(armed, stemps, temps)
            topk = jnp.where(armed, stopk, topk)
            topp = jnp.where(armed, stopp, topp)
            hist = jnp.where(armed[:, None], shist, hist)
            hlen = jnp.where(armed, shlen, hlen)
            lane = jnp.arange(B)

            def draft_from_hist(hist, hlen):
                tail3 = hist[:, W - 3:]
                win3 = hist[:, idx3]  # [B, D3, 3]
                m3 = jnp.all(win3 == tail3[:, None, :], -1) & (
                    hlen[:, None] >= d3_vals[None, :] + 3
                )
                any3 = jnp.any(m3, 1)
                dstar3 = d3_vals[jnp.argmax(m3, 1)]
                tail2 = hist[:, W - 2:]
                win2 = hist[:, idx2]
                m2 = jnp.all(win2 == tail2[:, None, :], -1) & (
                    hlen[:, None] >= d2_vals[None, :] + 2
                )
                any2 = jnp.any(m2, 1)
                dstar2 = d2_vals[jnp.argmax(m2, 1)]
                dstar = jnp.where(any3, dstar3, dstar2)
                exists = any3 | any2
                gidx = jnp.minimum(
                    (W - dstar)[:, None] + jnp.arange(K)[None, :], W - 1
                )
                drafts = jnp.take_along_axis(hist, gidx, axis=1)  # [B, K]
                return exists, dstar, drafts

            def cond(c):
                i, done, full = c[0], c[4], c[5]
                return (i < nsteps) & jnp.any(~(done | full))

            def body(c):
                (i, tok, pos, cache, done, full, emitted, nemit, reason,
                 hist, hlen, nacc, ndr) = c
                rec = ~(done | full)
                room = budgets - nemit
                zeros_b = jnp.zeros((B,), jnp.int32)

                def _plain(cache):
                    logits, cache = run_forward(
                        params, tok[:, None], pos[:, None], cache, bt
                    )
                    nxt = sample_step(
                        logits[:, 0], keys[i], temps, topk, topp,
                        greedy_cond=greedy_cond, approx_topk=approx,
                    )
                    cand = jnp.concatenate(
                        [nxt[:, None], jnp.zeros((B, K), jnp.int32)], 1
                    )
                    return cache, cand, rec.astype(jnp.int32), zeros_b, zeros_b

                if inloop_spec:
                    exists, dstar, drafts = draft_from_hist(hist, hlen)
                    # draft only greedy active lanes with budget headroom;
                    # continuation length is capped by the match distance
                    # (the tokens that followed the matched occurrence)
                    dlen = jnp.where(
                        exists & (temps <= 0.0) & rec,
                        jnp.minimum(
                            jnp.minimum(dstar, K), jnp.maximum(room - 1, 0)
                        ),
                        0,
                    )

                    def _with_spec(cache):
                        toks = jnp.concatenate([tok[:, None], drafts], 1)
                        posm = jnp.minimum(
                            pos[:, None] + jnp.arange(K + 1)[None, :], scratch
                        )
                        logits, cache = run_forward(params, toks, posm, cache, bt)
                        greedy = jnp.argmax(logits, -1).astype(jnp.int32)
                        valid = jnp.arange(K)[None, :] < dlen[:, None]
                        ok = (drafts == greedy[:, :K]) & valid
                        a = jnp.cumprod(ok.astype(jnp.int32), 1).sum(1)
                        nxt0 = sample_step(
                            logits[:, 0], keys[i], temps, topk, topp,
                            greedy_cond=greedy_cond, approx_topk=approx,
                        )
                        # position j>0 emits the verifier's argmax: token j
                        # is either an accepted draft (== argmax by the
                        # acceptance rule) or the correction token
                        cand = jnp.concatenate([nxt0[:, None], greedy[:, 1:]], 1)
                        navail = jnp.where(rec, a + 1, 0)
                        return (
                            cache, cand, navail,
                            jnp.where(rec, a, 0), jnp.where(rec, dlen, 0),
                        )

                    cache, cand, navail, acc, dln = lax.cond(
                        jnp.any(dlen > 0), _with_spec, _plain, cache
                    )
                else:
                    cache, cand, navail, acc, dln = _plain(cache)

                navail = jnp.minimum(navail, jnp.maximum(room, 0))
                is_emit = jnp.arange(K + 1)[None, :] < navail[:, None]
                is_eos = is_emit & (cand == eos_id) & (~ign[:, None])
                has_eos = jnp.any(is_eos, 1)
                cnt = jnp.where(has_eos, jnp.argmax(is_eos, 1) + 1, navail)
                nemit = nemit + cnt
                reason = jnp.where((reason == 0) & has_eos, 1, reason)
                done = done | has_eos
                # cap-hit lanes FREEZE at their real tok/pos with reason 0:
                # the host rescan (authoritative for budget) either finishes
                # them or lets the already-pipelined next loop continue them
                full = full | (rec & ~has_eos & (nemit >= budgets))
                last = jnp.take_along_axis(
                    cand, jnp.maximum(cnt - 1, 0)[:, None], 1
                )[:, 0]
                tok = jnp.where(cnt > 0, last, tok)
                # EOS lanes park at scratch (finishing token recorded, never
                # fed); frozen/live lanes keep real positions
                pos = jnp.where(
                    done,
                    jnp.full_like(pos, scratch),
                    jnp.minimum(pos + cnt, scratch),
                )
                for j in range(K + 1):
                    ridx = jnp.where(j < cnt, nemit - cnt + j, cap_rows)
                    emitted = emitted.at[ridx, lane].set(
                        cand[:, j], mode="drop"
                    )
                ext = jnp.concatenate([hist, cand], 1)
                hist = jnp.take_along_axis(
                    ext, jnp.arange(W)[None, :] + cnt[:, None], 1
                )
                hlen = jnp.minimum(hlen + cnt, W)
                return (
                    i + 1, tok, pos, cache, done, full, emitted, nemit,
                    reason, hist, hlen, nacc + acc, ndr + dln,
                )

            init = (
                jnp.int32(0),
                tok,
                pos,
                cache,
                ~live | (budgets <= 0),
                jnp.zeros((B,), bool),
                jnp.full((cap_rows, B), -1, jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
                hist,
                hlen,
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
            )
            (i, tok, pos, cache, done, full, emitted, nemit, reason,
             hist, hlen, nacc, ndr) = lax.while_loop(cond, body, init)
            packed = jnp.concatenate(
                [
                    emitted,
                    nemit[None, :],
                    reason[None, :],
                    jnp.broadcast_to(i, (1, B)).astype(jnp.int32),
                    nacc[None, :],
                    ndr[None, :],
                ],
                axis=0,
            )
            return packed, tok, pos, temps, topk, topp, hist, hlen, cache

        if self.paged:

            def fused_paged(
                params, cache, bt, tok, pos, temps, topk, topp, hist, hlen,
                stok, spos, stemps, stopk, stopp, shist, shlen,
                armed, live, budgets, ign, keys, nsteps,
            ):
                return fused_body(
                    params, cache, tok, pos, temps, topk, topp, hist, hlen,
                    stok, spos, stemps, stopk, stopp, shist, shlen,
                    armed, live, budgets, ign, keys, nsteps, bt,
                )

            fn = self._fused_fns[self._fused_cap] = jax.jit(
                fused_paged, donate_argnums=(1, 3, 4, 5, 6, 7, 8, 9)
            )
        else:
            fn = self._fused_fns[self._fused_cap] = jax.jit(
                fused_body, donate_argnums=(1, 2, 3, 4, 5, 6, 7, 8)
            )
        return fn

    def warmup(self) -> None:
        """Pre-compile every serve-path signature BY SERVING: one synthetic
        request per reachable prefill bucket runs through the real worker
        machinery (admission → chunked prefill → device-carry injection →
        pipelined decode → finish/park), so the executable cache is
        populated with exactly the signatures real traffic produces —
        shapes AND argument placement. Hand-rolled device calls kept
        missing signatures (a committed first-token scalar vs an
        uncommitted placeholder re-compiles the same shapes), so the first
        real request still paid a compile (VERDICT r3 weak #6). Chunked
        prefill feeds at most ``prefill_chunk`` tokens per tick, so the
        reachable buckets are those ≤ bucket(min(prefill_chunk,
        max_seq-2)). Runs behind the loading marker — /health answers 503
        throughout; telemetry from warmup traffic is dropped at the end."""
        top_bucket = self._bucket(min(self.prefill_chunk, max(1, self.max_seq - 2)))
        filler = min(5, self.cfg.vocab_size - 1)

        async def _one(n_prompt: int, mt: int) -> None:
            loop = asyncio.get_running_loop()
            req = GenRequest(
                id="",
                session="",
                prompt_ids=[self.tokenizer.bos_id] + [filler] * (n_prompt - 1),
                max_tokens=mt,
                temperature=0.0,
                loop=loop,
                future=loop.create_future(),
            )
            self._queue.put(req)
            await req.future

        async def _serve_all() -> None:
            for b in PREFILL_BUCKETS:
                if b > top_bucket:
                    break
                # land exactly in bucket b: the longest admissible prompt
                # caps at max_seq-2 (budget with max_tokens=1), so undersized
                # arenas still reach their top bucket
                n = max(1, min(b, self.max_seq - 2))
                mt = max(1, min(self.decode_chunk, self.max_seq - 1 - n))
                await _one(n, mt)
            if self.decode_steps == 0:
                # pathological shapes can finish every bucket pass without a
                # decode chunk; force one so decode compiles here, not at
                # the first real request
                await _one(1, min(self.decode_chunk + 1, max(2, self.max_seq // 2)))
            # compile the adaptive chunk ladder: each bucket is its own
            # lax.scan length (its own executable). max_tokens = c + 1 makes
            # the remaining budget after the prefill-sampled first token
            # exactly c, so the dispatcher picks bucket c.
            for c in self._decode_ladder:
                if c >= self.decode_chunk:
                    break  # the full chunk compiled in the passes above
                await _one(1, min(c + 1, max(2, self.max_seq - 2)))

        # dedicated thread: asyncio.run must not land on a thread that is
        # already inside a running loop (LLMEngine.create is called from
        # async tests and from the serve app's loader thread alike)
        box: list[BaseException] = []

        def _runner() -> None:
            try:
                asyncio.run(_serve_all())
            except BaseException as e:  # surface warmup faults to create()
                box.append(e)

        # the arena stays OFF while warmup serves: the bucket passes share a
        # filler-token prefix, and a prefix hit would shrink a pass's tail
        # below its bucket — exactly the prefill signature warmup exists to
        # compile. The fork/slice fns are warmed explicitly below instead.
        # Speculation is OFF too: the filler prompts are maximally
        # repetitive, and a spec round replacing a decode chunk would leave
        # ladder buckets uncompiled. The verify ladder is warmed explicitly.
        self._prefix_active = False
        self._spec_active = False
        try:
            t = threading.Thread(target=_runner, name="llm-warmup")
            t.start()
            t.join()
        finally:
            self._prefix_active = self.prefix_cache
            self._spec_active = self.speculative
        if box:
            raise box[0]
        # pre-compile the snapshot slicers too: their first jit used to
        # land on the serving worker thread mid-traffic, stalling every
        # in-flight decode for the compile's duration — tens of seconds on
        # a tunneled chip, which 502'd the round-4 flagship bench run
        if self.paged:
            # paged snapshot stagers: exact-page-count gathers, warmed at
            # pow2 counts (odd counts compile on demand — a trivial gather)
            c = 1
            while True:
                count = min(c, self._n_blocks)
                ids = jnp.zeros((count,), jnp.int32)
                jax.block_until_ready(self._snap_fn_paged(count)(self.cache, ids))
                if c >= self._n_blocks:
                    break
                c *= 2
        else:
            b = PREFILL_BUCKETS[0]
            snap_buckets = set()
            while True:
                snap_buckets.add(min(b, self.max_seq))
                if b >= self.max_seq:
                    break
                b *= 2
            for bucket in sorted(snap_buckets):
                jax.block_until_ready(self._snap_fn(bucket)(self.cache, jnp.int32(0)))
        # prefix-arena copy fns (same warm-up pattern as the snapshot
        # slicers): one slice + one fork executable per bucket level, so an
        # admission-time fork never pays a serve-time compile. The fork
        # round-trips slot 0's own rows — it writes back exactly what it
        # read, so warmed state is untouched. Paged engines fork by PAGE
        # MAPPING (no compiled copy at all); only the partial-tail CoW
        # single-page copy needs warming.
        if self.prefix_cache and self.paged:
            scr = jnp.int32(self._scratch_page(0))
            self.cache = self._page_copy_fn()(self.cache, scr, scr)
            jax.block_until_ready(self.cache.k)
        elif self.prefix_cache:
            for b in self._prefix_levels:
                k, v = self._prefix_slice_fn(b)(self.cache, jnp.int32(0))
                self.cache = self._prefix_fork_fn(b)(
                    self.cache, jnp.int32(0), k, v
                )
            jax.block_until_ready(self.cache.k)
        # verify ladder (speculative decoding): one compiled k-token verify
        # program per bucket, exercised against the live carry/cache — all
        # lanes are parked at scratch here, so the round's writes land in
        # the scratch rows exactly like plain parked decode. A serving-time
        # spec round must never pay a compile.
        if self.speculative:
            for b in self._spec_buckets:
                self._rng, key = jax.random.split(self._rng)
                _, _, self._dtok, self._dpos, self.cache = self._verify_fn(b)(
                    self.params,
                    self.cache,
                    *self._bt_arg(),
                    self._dtok,
                    self._dpos,
                    self._dtemps,
                    self._dtopk,
                    self._dtopp,
                    jnp.zeros((self.max_batch, b), jnp.int32),
                    jnp.zeros((self.max_batch,), jnp.int32),
                    key,
                )
            jax.block_until_ready(self.cache.k)
        # warmup traffic is not serving telemetry: TTFT samples here include
        # compile time and would pollute p50s until the deque rolls over
        self.clear_sessions()
        self.ttft_ms_recent.clear()
        self.itl_ms_recent.clear()
        self.admission_ms_recent.clear()
        self.prefill_ms_recent.clear()
        self.first_readback_ms_recent.clear()
        self.decode_chunk_hist = {}
        self.decode_chunks_shrunk = 0
        self.fused_loops_total = 0
        self.fused_steps_total = 0
        self.fused_early_exits_total = 0
        self.fused_exit_reason_hist = {}
        self.fused_injections_total = 0
        self.fused_inject_fallbacks_total = 0
        self.inloop_spec_drafted = 0
        self.inloop_spec_accepted = 0
        self.host_syncs_total = 0
        self._prefix_entries.clear()
        self._prefix_bytes = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0
        self.prefix_evictions = 0
        self.session_evictions = 0
        self.session_eviction_idle_s_recent.clear()
        self.prefix_eviction_idle_s_recent.clear()
        self.tokens_generated = 0
        self.prefills = 0
        self.decode_steps = 0
        self._occupancy_sum = 0.0
        self.flops_done = 0.0
        self.hbm_bytes_read = 0.0
        self._last_decode_end = None
        if self.paged:
            # warmup's anonymous sessions already freed their pages at
            # finish; reclaim anything still quarantined and zero the
            # pool-telemetry counters so serving starts from a clean gauge
            self._release_quarantine()
            self.page_exhausted_total = 0
            self.pages_truncated = 0
            self.prefix_pages_shared = 0
        self._started_at = time.monotonic()

    # -- public API (called from the aiohttp loop) ------------------------
    def load_depth(self) -> int:
        """Submit-side load estimate: queued + drained-but-unadmitted +
        in-flight GENERATION requests. Snapshot/restore commands ride the
        same queue but are not admission load — counting them would shed
        serveable traffic whenever per-turn KV snapshots burst. Approximate
        by design: admission control needs a watermark comparison, not an
        exact census."""
        with self._queue.mutex:
            queued = sum(1 for it in self._queue.queue if isinstance(it, GenRequest))
        return (
            queued
            + sum(1 for it in self._waiting if isinstance(it, GenRequest))
            + sum(1 for s in self.slots if s.request is not None)
        )

    async def generate(
        self,
        prompt: str,
        max_tokens: int = 64,
        temperature: float = 0.0,
        request_id: str = "",
        session: str = "",
        deadline_at: float | None = None,
        ignore_eos: bool = False,
        top_k: int = 0,
        top_p: float = 1.0,
        emit=None,
    ) -> dict:
        if request_id:
            with self._lock:
                hit = self._completed.get(request_id)
            if hit is not None:
                return dict(hit, replayed=True)
        # failpoint: submit-side fault (chaos soak's "engine rejects work")
        # — surfaces to the serve layer exactly like any submit error
        await faults.fire_async("engine.submit")
        if self._draining:
            raise EngineDraining("engine draining for shutdown")
        if self.deadlines and self.shed_watermark:
            depth = self.load_depth()
            if depth >= self.shed_watermark:
                self.shed_total += 1
                raise EngineOverloaded(depth, self.shed_watermark)
        loop = asyncio.get_running_loop()
        prompt_ids = self.tokenizer.encode(prompt)
        req = GenRequest(
            id=request_id or f"gen-{time.monotonic_ns()}",
            session=session,
            prompt_ids=prompt_ids,
            max_tokens=max(1, max_tokens),
            temperature=temperature,
            loop=loop,
            future=loop.create_future(),
            deadline_at=deadline_at if self.deadlines else None,
            ignore_eos=ignore_eos,
            top_k=max(0, int(top_k)),
            top_p=min(1.0, max(0.0, float(top_p))) if top_p is not None else 1.0,
            emit=emit,
        )
        self._queue.put(req)
        result = await req.future
        if request_id:
            with self._lock:
                self._completed[request_id] = result
                while len(self._completed) > 512:
                    self._completed.popitem(last=False)
        return result

    async def chat(
        self,
        session: str,
        message: str,
        max_tokens: int = 64,
        request_id: str = "",
        deadline_at: float | None = None,
        ignore_eos: bool = False,
        emit=None,
    ) -> dict:
        return await self.generate(
            prompt=message,
            max_tokens=max_tokens,
            temperature=0.0,
            request_id=request_id,
            session=session or "default",
            deadline_at=deadline_at,
            ignore_eos=ignore_eos,
            emit=emit,
        )

    def cancel(self, request_id: str) -> bool:
        """Request-id cancel path (client disconnected / operator abort).
        Queued or waiting items are rejected before prefill; an in-flight
        lane is parked mid-decode on the next worker iteration and its slot
        freed for admission. Returns False for ids already completed (the
        memoized result stands — a replay may still claim it); True means
        the abort was recorded and the worker will act on it."""
        if not request_id:
            return False
        with self._lock:
            if request_id in self._completed:
                return False
            self._cancel_requested[request_id] = time.monotonic()
        return True

    async def snapshot_session(self, session: str) -> bytes | None:
        """Serialize a session's live KV prefix for the store.

        Two stages: the WORKER thread stages the slot's prefix into fresh
        cache-dtype device buffers (bounded bucket shapes — a handful of compiled
        slice programs, instead of one XLA program per distinct position),
        then the npz pack + blocking device→host readback runs in an
        executor thread so neither the worker nor the event loop stalls on
        the transfer.
        """
        loop = asyncio.get_running_loop()
        # failpoint: snapshot-serialize fault — surfaces through the serve
        # layer's kv_snapshot_errors counter, never into the decode path
        await faults.fire_async("engine.snapshot")
        staged = None
        for _ in range(5):  # global limiter may ask us to come back later
            cmd = SnapshotCmd(session=session, loop=loop, future=loop.create_future())
            self._queue.put(cmd)
            staged = await cmd.future
            if staged != "rate-limited":
                break
            await asyncio.sleep(self.snapshot_min_gap_s)
        if staged == "rate-limited":
            # distinguishable give-up: the caller decides whether to retry
            # later or surface it — silently returning None here would be
            # indistinguishable from "session has nothing to save"
            raise SnapshotDeferred(session)
        if staged is None:
            return None
        k16, v16, position, pending_token = staged
        from .checkpoint import pack_kv_snapshot

        meta = {"session": session, "pending_token": pending_token}
        if self.paged:
            # staged from live pages only (ceil(position/page_size) pages,
            # not a pow2 position bucket); payload layout is identical to
            # the dense staging so blobs restore across both arenas
            meta["page_size"] = self.page_size
        return await asyncio.to_thread(
            pack_kv_snapshot,
            k16,
            v16,
            position,
            meta,
        )

    def _do_snapshot(self, cmd: SnapshotCmd) -> None:
        """Worker-thread half of snapshot_session: dispatch the bucketed
        slice (async on the device queue) and hand the staged buffers to the
        caller. No blocking readback here — decode keeps flowing."""
        if self.paged:
            sess = self.paged_sessions.get(cmd.session)
            if sess is None:
                cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, None)
                return
            self._snap_last_by_session.setdefault(cmd.session, time.monotonic())
            if sess.lane is not None and self.slots[sess.lane].request is not None:
                if cmd.session in self._snap_parked:
                    cmd.loop.call_soon_threadsafe(
                        _resolve_value, cmd.future, "rate-limited"
                    )
                else:
                    self._snap_parked[cmd.session] = cmd
                return
            self._stage_snapshot_paged(cmd, sess)
            return
        idx = self.sessions.get(cmd.session)
        if idx is None:
            cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, None)
            return
        # the durability clock for a session starts at its FIRST snapshot
        # attempt (not engine boot): a fresh session under load stages
        # within snapshot_force_s of its first turn, no sooner
        self._snap_last_by_session.setdefault(cmd.session, time.monotonic())
        slot = self.slots[idx]
        if slot.request is not None:
            # mid-generation: PARK the command on the session and stage at
            # the request's finish — that instant is an idle-slot moment by
            # construction, so under back-to-back turns the snapshot can
            # never lose the race with the next admission (round-5 bench:
            # the "try now, give up if busy" policy produced kv_snapshots=0
            # under load). One parked command per session; extras bounce.
            if cmd.session in self._snap_parked:
                cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, "rate-limited")
            else:
                self._snap_parked[cmd.session] = cmd
            return
        self._stage_snapshot(cmd, slot)

    def _snap_gate(self, session: str) -> bool:
        """Shared staging limiter (dense slot and paged session alike):
        True = rate-limited this time. A snapshot's device→host readback
        serializes with decode on the device link (measured ~1.25s for an
        8B bucket-128 blob over the tunnel), so stagings are spaced out;
        the per-session durability floor forces one through eventually."""
        now = time.monotonic()
        busy = any(s.decoding or s.pending_prompt for s in self.slots)
        # durability floor is PER SESSION: with a global timer, whichever
        # session staged first reset it for everyone and the other sessions
        # starved for N×30s under sustained multi-session load
        session_last = self._snap_last_by_session.get(session, self._snap_epoch0)
        overdue = now - session_last >= self.snapshot_force_s
        # busy stagings are spaced wider: each one costs ~a second of device
        # link the in-flight generations are using, so under sustained load
        # the per-session floor degrades gracefully to ~n_sessions×busy_gap
        gap = self.snapshot_busy_gap_s if busy else self.snapshot_min_gap_s
        gap_ok = now - self._last_snapshot_at >= gap
        return (not gap_ok) or (busy and not overdue)

    def _stage_snapshot(self, cmd: SnapshotCmd, slot: Slot) -> None:
        """Stage a settled slot's prefix (worker thread), limiter-gated."""
        staged = None
        if self._snap_gate(cmd.session):
            staged = "rate-limited"
        elif slot.position > 0:
            now = time.monotonic()
            self._last_snapshot_at = now
            self._snap_last_by_session[cmd.session] = now
            k16, v16 = self._snap_fn(self._snap_bucket(slot.position))(
                self.cache, jnp.int32(slot.idx)
            )
            try:
                k16.copy_to_host_async()
                v16.copy_to_host_async()
            except Exception:
                pass
            staged = (k16, v16, slot.position, slot.pending_token)
        cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, staged)

    def _stage_snapshot_paged(self, cmd: SnapshotCmd, sess: PagedSession) -> None:
        """Paged staging: gather ONLY the session's live pages into a
        contiguous buffer — a 100-token session ships 2 pages, not a pow2
        position bucket — same limiter, same exact-dtype discipline."""
        staged = None
        if self._snap_gate(cmd.session):
            staged = "rate-limited"
        elif sess.position > 0 and sess.pages:
            now = time.monotonic()
            self._last_snapshot_at = now
            self._snap_last_by_session[cmd.session] = now
            count = min(
                len(sess.pages), (sess.position - 1) // self.page_size + 1
            )
            ids = jnp.asarray(np.asarray(sess.pages[:count], dtype=np.int32))
            k16, v16 = self._snap_fn_paged(count)(self.cache, ids)
            try:
                k16.copy_to_host_async()
                v16.copy_to_host_async()
            except Exception:
                pass
            staged = (k16, v16, sess.position, sess.pending_token)
        cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, staged)

    def _service_parked_snapshot(self, slot: Slot) -> None:
        """Called at a request's finish: stage any snapshot parked on this
        session while the slot is provably idle."""
        cmd = self._snap_parked.pop(slot.session, None) if slot.session else None
        if cmd is not None:
            if self.paged and slot.psess is not None:
                self._stage_snapshot_paged(cmd, slot.psess)
            else:
                self._stage_snapshot(cmd, slot)

    def _flush_parked_snapshot(self, session: str) -> None:
        """Session going away (eviction/reset/clear): a parked snapshot
        command must resolve rather than hang its caller forever."""
        self._snap_last_by_session.pop(session, None)
        cmd = self._snap_parked.pop(session, None)
        if cmd is not None:
            cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, None)

    def _snap_bucket(self, position: int) -> int:
        """Next power of two ≥ position, capped at max_seq — a handful of
        compiled snapshot-slice shapes total (NOT one per position, and not
        capped at the prefill buckets' 1024: long-context sessions past
        1024 tokens must not have their tails silently truncated)."""
        b = PREFILL_BUCKETS[0]
        while b < position:
            b *= 2
        return min(b, self.max_seq)

    def _snap_fn(self, bucket: int):
        fn = self._snap_fns.get(bucket)
        if fn is None:

            def _snap(cache, i, _b=bucket):
                # EXACT dtype, no fp16 round-trip: the snapshot restores
                # into the same-dtype arena, and "resume token-identical"
                # is a bit-equality claim — an fp16 staging cast rounded
                # fp32/bf16 KV and flipped near-tie greedy argmaxes after
                # restore (found by the chaos soak's resume invariant).
                # bf16/fp16 caches ship 2 bytes/elem as before; fp32 CPU
                # caches pay 2x blob size for exactness.
                k = lax.dynamic_slice_in_dim(cache.k, i, 1, axis=1)[:, 0, :_b]
                v = lax.dynamic_slice_in_dim(cache.v, i, 1, axis=1)[:, 0, :_b]
                return k, v

            fn = self._snap_fns[bucket] = jax.jit(_snap)
        return fn

    # -- tiered KV hierarchy: device → pinned host RAM → store ------------
    #
    # Parking reuses the snapshot plane's staging fns (exact dtype, bounded
    # shapes) and the pool's quarantine discipline for the freed pages;
    # promotion reuses the restore fns. Tier transfers are pure data
    # movement — no new compiled variants, ever (recompile budget 0).

    async def park_session(self, session: str) -> bytes | None:
        """Demote an idle session's KV off the device into the host RAM
        tier and return its exact SNAP_VERSION 3 blob for the store (the
        cold tier — survives the process and the host tier's LRU budget).
        None: tiering off, session unknown/busy, or the demote failpoint
        fired — in every case the session is left exactly as it was."""
        if not self.kv_tiering:
            return None
        loop = asyncio.get_running_loop()
        cmd = ParkCmd(session=session, loop=loop, future=loop.create_future())
        self._queue.put(cmd)
        staged = await cmd.future
        if staged is None:
            return None
        k, v, position, pending_token = staged
        from .checkpoint import pack_kv_snapshot

        meta = {"session": session, "pending_token": pending_token}
        if self.paged:
            meta["page_size"] = self.page_size
        return await asyncio.to_thread(pack_kv_snapshot, k, v, position, meta)

    async def prewarm_session(self, session: str) -> bool:
        """Promote a host-tier session back onto the device ahead of its
        next turn (the proxy's next-arrival hint). True when the session
        is device-resident afterwards (including already-resident)."""
        if not self.kv_tiering:
            return False
        loop = asyncio.get_running_loop()
        cmd = PrewarmCmd(session=session, loop=loop, future=loop.create_future())
        self._queue.put(cmd)
        return bool(await cmd.future)

    def has_session(self, session: str) -> bool:
        """Membership across tiers: device-resident OR parked in host RAM.
        The serve layer asks this instead of ``in sessions`` so a parked
        session is never mistaken for unknown (which would store-restore
        stale context and re-prepend the system prompt — duplicated
        context breaks resume parity)."""
        if session in self.sessions:
            return True
        with self._tier_lock:
            return session in self._host_tier

    def _do_park(self, cmd: ParkCmd) -> None:
        """Worker half of park_session: demote and hand the exact staged
        host arrays back for the caller's store blob."""
        staged = self._tier_demote(cmd.session) if self.kv_tiering else None
        cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, staged)

    def _do_prewarm(self, cmd: PrewarmCmd) -> None:
        ok = self._tier_promote(cmd.session, prewarm=True) if self.kv_tiering else False
        cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, ok)

    def _tier_needs_promote(self, item) -> bool:
        """Admission-path check: this request's session is parked in host
        RAM and must swap in before _try_admit can see it."""
        return (
            self.kv_tiering
            and isinstance(item, GenRequest)
            and bool(item.session)
            and item.session not in self.sessions
            and item.session in self._host_tier
        )

    def _tier_demote(self, session: str, pressure: bool = False):
        """Worker thread: stage an idle session's exact KV prefix to host,
        free its device residency (pages via the quarantine discipline),
        and insert the host-tier entry (int8 per-page-scale quantized when
        tier_quantize is on). Returns the exact (k, v, position,
        pending_token) host arrays on success — the store blob is packed
        from THESE, before any quantization, so the cold tier keeps the
        bit-exact resume guarantee — or None with the session untouched."""
        try:
            # failpoint: a failed demote means the session simply STAYS
            # device-resident — parking is an optimization, never a
            # correctness step
            faults.fire("engine.kv_demote")
        except Exception:
            self.tier_demote_failures_total += 1
            return None
        if self.paged:
            sess = self.paged_sessions.get(session)
            if (
                sess is None
                or sess.lane is not None
                or not sess.pages
                or sess.position <= 0
            ):
                return None
            count = min(len(sess.pages), (sess.position - 1) // self.page_size + 1)
            ids = jnp.asarray(np.asarray(sess.pages[:count], dtype=np.int32))
            k16, v16 = self._snap_fn_paged(count)(self.cache, ids)
            # block on the gather BEFORE freeing the pages: the staged
            # buffers are fresh arrays, but materializing them proves the
            # read finished, so the freed pages can't be rewritten under it
            k = np.asarray(k16)[:, : sess.position]
            v = np.asarray(v16)[:, : sess.position]
            position, pending = sess.position, sess.pending_token
            spec = (list(sess.spec_hist), sess.spec_ema, sess.spec_miss)
            with self._page_lock:
                self._flush_parked_snapshot(session)
                self._free_session_pages(sess)
                self.paged_sessions.pop(session, None)
                self.sessions.pop(session, None)
        else:
            idx = self.sessions.get(session)
            if idx is None or idx < 0:
                return None
            slot = self.slots[idx]
            if slot.request is not None or slot.position <= 0:
                return None
            k16, v16 = self._snap_fn(self._snap_bucket(slot.position))(
                self.cache, jnp.int32(slot.idx)
            )
            k = np.asarray(k16)[:, : slot.position]
            v = np.asarray(v16)[:, : slot.position]
            position, pending = slot.position, slot.pending_token
            spec = (list(slot.spec_hist), slot.spec_ema, slot.spec_miss)
            self._flush_parked_snapshot(session)
            self.sessions.pop(session, None)
            slot.session = ""
            slot.position = 0
            slot.pending_token = None
            slot.prefix_ctx = None
            slot.spec_hist = []
            slot.spec_ema = 1.0
            slot.spec_miss = 0
            slot.epoch += 1
        if self.tier_quantize:
            from .quant import quantize_kv_pages

            qk, sk = quantize_kv_pages(k, self.page_size)
            qv, sv = quantize_kv_pages(v, self.page_size)
            entry = TieredEntry(
                k=qk,
                v=qv,
                k_scale=sk,
                v_scale=sv,
                quantized=True,
                pages=int(qk.shape[1]),
                position=position,
                pending_token=pending,
                nbytes=qk.nbytes + qv.nbytes + sk.nbytes + sv.nbytes,
                parked_at=time.monotonic(),
                spec_hist=spec[0],
                spec_ema=spec[1],
                spec_miss=spec[2],
            )
        else:
            entry = TieredEntry(
                k=k,
                v=v,
                position=position,
                pending_token=pending,
                nbytes=k.nbytes + v.nbytes,
                parked_at=time.monotonic(),
                spec_hist=spec[0],
                spec_ema=spec[1],
                spec_miss=spec[2],
            )
        self._tier_insert_host(session, entry, pressure=pressure)
        return k, v, position, pending

    def _tier_drop_locked(self, session: str):
        """Remove a host-tier entry + its gauge contribution. Caller holds
        _tier_lock. Returns the entry (or None)."""
        entry = self._host_tier.pop(session, None)
        if entry is not None:
            self.tier_host_bytes -= entry.nbytes
            if entry.quantized:
                self.tier_quantized_pages -= entry.pages
        return entry

    def _tier_insert_host(self, session: str, entry, pressure: bool = False) -> None:
        with self._tier_lock:
            self._tier_drop_locked(session)
            self._host_tier[session] = entry
            self._host_tier.move_to_end(session)
            self.tier_host_bytes += entry.nbytes
            if entry.quantized:
                self.tier_quantized_pages += entry.pages
            self.tier_demotions_total += 1
            if pressure:
                self.tier_pressure_demotions_total += 1
            # host budget: LRU entries fall through to the store-only cold
            # tier (their blob was written at park; the serve layer's
            # restore-on-unknown path serves their next turn)
            while (
                self.tier_host_bytes > self.tier_host_budget_bytes
                and len(self._host_tier) > 1
            ):
                oldest = next(iter(self._host_tier))
                self._tier_drop_locked(oldest)
                self.tier_host_evictions_total += 1

    def _tier_promote(self, session: str, prewarm: bool = False) -> bool:
        """Worker thread: swap a host-tier session back onto the device.
        The restore dispatch is ASYNC (no readback) — called from the
        admission path it overlaps the queue-wait phase of the returning
        turn's TTFT. On failure the entry stays parked and False returns
        (the admission path maps it to typed 429 backpressure)."""
        with self._tier_lock:
            entry = self._host_tier.get(session)
        if entry is None:
            return session in self.sessions
        t0 = time.monotonic()
        try:
            faults.fire("engine.kv_promote")
        except Exception:
            self.tier_promote_failures_total += 1
            return False
        if entry.quantized:
            from .quant import dequantize_kv_pages

            k = dequantize_kv_pages(entry.k, entry.k_scale, entry.position)
            v = dequantize_kv_pages(entry.v, entry.v_scale, entry.position)
        else:
            k, v = entry.k, entry.v
        if self.paged:
            ok = self._tier_promote_paged(session, entry, k, v)
        else:
            ok = self._tier_promote_dense(session, entry, k, v)
        if not ok:
            self.tier_promote_failures_total += 1
            return False
        with self._tier_lock:
            self._tier_drop_locked(session)
        self.tier_promotions_total += 1
        if prewarm:
            self.tier_prewarm_hits_total += 1
        if len(self._tier_promote_started) > 256:
            cutoff = t0 - 300.0
            for name in [
                n for n, t in self._tier_promote_started.items() if t < cutoff
            ]:
                self._tier_promote_started.pop(name, None)
        self._tier_promote_started[session] = t0
        return True

    def _tier_promote_paged(self, session: str, entry, k, v) -> bool:
        if entry.position <= 0 or entry.position >= self.max_seq - 1:
            return False
        if session in self.paged_sessions:
            return True  # already resident (stale host entry; caller drops it)
        count = (entry.position - 1) // self.page_size + 1
        try:
            ids = self._alloc_pages(count, serving=False)
        except EngineOverloaded:
            return False
        k = np.asarray(k)
        v = np.asarray(v)
        pad = count * self.page_size - k.shape[1]
        if pad:
            widths = [(0, 0), (0, pad)] + [(0, 0)] * (k.ndim - 2)
            k = np.pad(k, widths)
            v = np.pad(v, widths)
        dtype = self.cache.k.dtype
        shape = (k.shape[0], count, self.page_size, *k.shape[2:])
        self.cache = self._restore_fn_paged(count)(
            self.cache,
            jnp.asarray(np.asarray(ids, dtype=np.int32)),
            jnp.asarray(k.reshape(shape), dtype),
            jnp.asarray(v.reshape(shape), dtype),
        )
        sess = PagedSession(
            name=session,
            pages=ids,
            position=entry.position,
            pending_token=entry.pending_token,
            last_used=time.monotonic(),
            spec_hist=list(entry.spec_hist),
            spec_ema=entry.spec_ema,
            spec_miss=entry.spec_miss,
        )
        with self._page_lock:
            self.paged_sessions[session] = sess
            self.sessions[session] = -1
        return True

    def _tier_promote_dense(self, session: str, entry, k, v) -> bool:
        from .checkpoint import restore_kv_slot

        if entry.position <= 0 or entry.position >= self.max_seq - 1:
            return False
        slot = self._find_slot(session)
        if slot is None:
            return False
        self.cache = restore_kv_slot(self.cache, slot.idx, k, v)
        slot.position = entry.position
        slot.pending_token = entry.pending_token
        slot.last_used = time.monotonic()
        slot.spec_hist = list(entry.spec_hist)
        slot.spec_ema = entry.spec_ema
        slot.spec_miss = entry.spec_miss
        return True

    def _tier_pressure_demote(self, need: int) -> None:
        """Pool pressure (paged, worker thread, OUTSIDE _page_lock — the
        staging readback blocks): demote idle resident sessions LRU-first
        to the host tier until ``need`` pages are coverable. Where
        _reclaim_pages destroys the victim's context, demotion preserves
        it — a would-be 429 becomes a slower-but-served admission and the
        victim's next turn promotes instead of re-prefilling."""
        if not (self.kv_tiering and self.paged):
            return

        def short() -> bool:
            with self._page_lock:
                return len(self._page_free) + len(self._page_quarantine) < need

        while short():
            victim = None
            with self._page_lock:
                for sess in self.paged_sessions.values():
                    if sess.lane is not None or not sess.pages or sess.position <= 0:
                        continue
                    if victim is None or sess.last_used < victim.last_used:
                        victim = sess
            if victim is None:
                return
            if self._tier_demote(victim.name, pressure=True) is None:
                return  # demote failpoint or raced a new turn: stop, don't spin

    def _tier_metrics(self) -> dict:
        with self._tier_lock:
            host_sessions = len(self._host_tier)
            host_bytes = self.tier_host_bytes
            quantized_pages = self.tier_quantized_pages
        overlap = sorted(self.tier_promote_overlap_ms_recent)
        return {
            "kv_tiering": self.kv_tiering,
            "tier_quantize": self.tier_quantize,
            "tier_host_sessions": host_sessions,
            "tier_host_bytes": host_bytes,
            "tier_host_budget_bytes": self.tier_host_budget_bytes,
            "tier_quantized_pages": quantized_pages,
            "tier_demotions_total": self.tier_demotions_total,
            "tier_promotions_total": self.tier_promotions_total,
            "tier_pressure_demotions_total": self.tier_pressure_demotions_total,
            "tier_prewarm_hits_total": self.tier_prewarm_hits_total,
            "tier_demote_failures_total": self.tier_demote_failures_total,
            "tier_promote_failures_total": self.tier_promote_failures_total,
            "tier_host_evictions_total": self.tier_host_evictions_total,
            "tier_promote_overlap_ms_total": round(
                self.tier_promote_overlap_ms_total, 2
            ),
            "tier_promote_overlap_ms_p50": (
                round(overlap[len(overlap) // 2], 2) if overlap else None
            ),
        }

    # -- paged arena: page allocator + block tables -----------------------
    #
    # Host-side bookkeeping for the device page pool. The free list /
    # refcounts / block table live in numpy under _page_lock (the worker
    # allocates; API threads clear sessions), and the table ships to the
    # device as a per-dispatch argument. Refcounting is what makes prefix
    # sharing zero-copy: a cached prefix PINS its pages, sessions map them
    # read-only (they never write below their fork point), and a page is
    # returned to the free list only when its last reference drops.

    def _scratch_page(self, lane: int) -> int:
        """Lane ``lane``'s dedicated scratch page: every block-table entry
        not covered by the bound session's pages points here, so parked
        decode steps and bucket-padding writes land in per-lane garbage
        that no live query's position mask ever exposes."""
        return self._data_pages + lane

    def _bt_arg(self) -> tuple:
        """The block-table positional argument the paged compiled fns take
        between ``cache`` and the token state — empty in dense mode, so
        shared call sites splat it instead of duplicating argument lists."""
        return (jnp.asarray(self._bt),) if self.paged else ()

    def _alloc_pages(
        self, n: int, serving: bool = True, reclaim: bool = True
    ) -> list[int]:
        """Take ``n`` pages off the free list, evicting idle resident
        sessions (then unpinning prefix entries) LRU-first when the list
        runs dry. Raises PagePoolExhausted — mapped to 429 backpressure by
        the serve layer — when reclaim cannot cover the need; the pool
        being full of in-flight work is overload, not a fault."""
        if n <= 0:
            return []
        if serving:
            # failpoint: deterministic pool-exhaustion injection (chaos
            # soak). Any injected error surfaces as the same backpressure
            # a genuinely full pool produces — never a crash.
            try:
                faults.fire("engine.page_alloc")
            except Exception as e:
                self.page_exhausted_total += 1
                with self._page_lock:
                    free = len(self._page_free)
                raise PagePoolExhausted(n, free) from e
        self._reap_quarantine_if_short(n)
        if reclaim and self.kv_tiering:
            with self._page_lock:
                tier_short = (
                    len(self._page_free) + len(self._page_quarantine) < n
                )
            if tier_short:
                # demote idle residents to the HOST TIER before destructive
                # reclaim: parked context survives for its next turn, and
                # the freed pages convert a would-be 429 into admission
                self._tier_pressure_demote(n)
                self._reap_quarantine_if_short(n)
        with self._page_lock:
            if len(self._page_free) < n and reclaim:
                self._reclaim_pages(n)
        # eviction frees land in quarantine while readbacks are in flight;
        # take them back before declaring exhaustion
        self._reap_quarantine_if_short(n)
        with self._page_lock:
            if len(self._page_free) < n:
                if serving:
                    # only SERVING allocations are backpressure events: a
                    # best-effort internal alloc (prefix tail pin) failing
                    # must not inflate the 429 evidence counter
                    self.page_exhausted_total += 1
                raise PagePoolExhausted(n, len(self._page_free))
            ids = [self._page_free.pop() for _ in range(n)]
            for pid in ids:
                self._page_refs[pid] = 1
            return ids

    def _reclaim_pages(self, need: int) -> None:
        """Evict until ``need`` pages are free (or nothing evictable is
        left): idle resident sessions LRU-first — they can re-prefill (or
        restore from their store snapshot) — then prefix-arena pins, which
        only cost the next cold prefill. In-flight sessions are never
        touched. Caller holds _page_lock. Quarantined pages COUNT toward
        the goal (the caller reaps them right after): with readbacks in
        flight every eviction's pages land in quarantine, and a loop
        watching only the free list would keep evicting — one transient
        one-page shortfall wiping every idle resident and prefix pin."""

        def short() -> bool:
            return len(self._page_free) + len(self._page_quarantine) < need

        while short():
            victim = None
            for sess in self.paged_sessions.values():
                if sess.lane is not None or not sess.pages:
                    continue
                if victim is None or sess.last_used < victim.last_used:
                    victim = sess
            if victim is None:
                break
            self._count_eviction("session", time.monotonic() - victim.last_used)
            self._free_session_pages(victim)
            self.paged_sessions.pop(victim.name, None)
            self.sessions.pop(victim.name, None)
            self._flush_parked_snapshot(victim.name)
        now = time.monotonic()
        while short() and any(
            e.pages is not None for e in self._prefix_entries.values()
        ):
            self._prefix_evict_lru(now)

    def _free_page_ids(self, ids: list[int]) -> None:
        """Return zero-ref pages to the free list — via quarantine when
        readbacks are in flight: a chunk dispatched before the free holds
        the OLD device block table and will still write into these pages,
        so reallocating them before its readback drains would let parked
        garbage corrupt another session's KV."""
        if not ids:
            return
        with self._page_lock:
            if self._readbacks:
                self._page_quarantine.extend(ids)
            else:
                self._page_free.extend(ids)

    def _reap_quarantine_if_short(self, need: int) -> None:
        """Allocation-path quarantine release (worker thread): when the
        free list can't cover ``need``, WAIT for the in-flight device work
        to finish — NOT for the readback FIFO to process. Draining the
        FIFO here would run admissions and finishes in the middle of a
        dispatch whose lane snapshot the caller already captured, desyncing
        token delivery. Every cache-writing dispatch chains through the
        donated pool (self.cache is the newest link), so the current
        cache being ready proves every stale-block-table write has landed
        and the whole quarantine is reallocatable. Token readbacks still
        pending in the FIFO are independent device arrays — releasing the
        pages under them is safe."""
        with self._page_lock:
            if len(self._page_free) >= need or not self._page_quarantine:
                return
        try:
            jax.block_until_ready(self.cache.k)
        except Exception:
            return  # can't prove the writes landed; quarantine stays parked
        with self._page_lock:
            self._page_free.extend(self._page_quarantine)
            self._page_quarantine = []

    def _release_quarantine(self) -> None:
        """Worker loop, once the readback FIFO is empty: every dispatch
        that could touch quarantined pages has drained."""
        if self._page_quarantine and not self._readbacks:
            with self._page_lock:
                if self._page_quarantine and not self._readbacks:
                    self._page_free.extend(self._page_quarantine)
                    self._page_quarantine = []

    def _decref_page(self, pid: int) -> None:
        with self._page_lock:
            self._page_refs[pid] -= 1
            if self._page_refs[pid] <= 0:
                self._page_refs[pid] = 0
                self._free_page_ids([pid])

    def _free_session_pages(self, sess: PagedSession) -> None:
        pages, sess.pages, sess.shared = sess.pages, [], 0
        for pid in pages:
            self._decref_page(pid)

    def _bind_lane_bt(self, slot: Slot, sess: PagedSession) -> None:
        """Point the lane's block-table row at the session's pages; every
        uncovered block falls back to the lane's scratch page."""
        self._bt[slot.idx, :] = self._scratch_page(slot.idx)
        if sess.pages:
            self._bt[slot.idx, : len(sess.pages)] = sess.pages

    def _ensure_lane_pages(self, slot: Slot, upto_pos: int, serving: bool) -> None:
        """Grow the bound session's page list (and the lane's table row) to
        cover writes through logical position ``upto_pos``. Called before
        every prefill/decode/verify dispatch so the compiled call never
        needs in-flight table growth; allocation failure surfaces as
        PagePoolExhausted for THIS request only."""
        sess = slot.psess
        if sess is None:
            return
        blocks = min(max(0, upto_pos), self.max_seq - 2) // self.page_size + 1
        have = len(sess.pages)
        if have >= blocks:
            return
        new = self._alloc_pages(blocks - have, serving=serving)
        sess.pages.extend(new)
        self._bt[slot.idx, have:blocks] = new

    def _truncate_session_pages(self, sess: PagedSession) -> None:
        """Page-tail truncation: free whole pages beyond the live context.
        This is what speculative rewind and chunk overshoot become in the
        paged arena — rejected-draft KV beyond ``position`` was already
        position-masked; here the PAGES holding only such garbage go back
        to the pool instead of staying pinned to the session."""
        with self._page_lock:
            keep = (
                0 if sess.position <= 0 else (sess.position - 1) // self.page_size + 1
            )
            keep = max(keep, sess.shared)  # never drop mapped prefix pages
            if len(sess.pages) <= keep:
                return
            tail = sess.pages[keep:]
            del sess.pages[keep:]
            if sess.lane is not None:
                # un-map the freed blocks from the live lane: a stale table
                # entry is read-masked but must never be WRITTEN through
                self._bt[sess.lane, keep:] = self._scratch_page(sess.lane)
            self.pages_truncated += len(tail)
            for pid in tail:
                self._decref_page(pid)

    def _rollback_lane_session(self, slot: Slot) -> None:
        """Paged lane reset for a POLICY failure (pool exhaustion → 429):
        unlike a fault, no dispatch died mid-write — the session's KV below
        its admission-time position is intact, and only this request's
        prefill/partial generation (which the recorded history will never
        contain) must go. Truncate back, restore the admission-time pending
        token, and keep the session RESIDENT: the client's Retry-After
        retry continues the conversation instead of finding it destroyed."""
        sess = slot.psess
        if sess is None or not sess.name or sess.admit_position <= 0:
            # fresh or anonymous context: nothing pre-request to preserve
            # (a fresh prefix-hit admission advanced position, but those
            # mapped tokens belong to the failed request — drop them too)
            self._drop_lane_session(slot)
            return
        with self._page_lock:
            slot.psess = None
            self._bt[slot.idx, :] = self._scratch_page(slot.idx)
            # roll position back too: the prefix map and speculative accept
            # syncs both advance it mid-request, and every such token
            # belongs to the request that just failed with 429
            sess.position = sess.admit_position
            sess.pending_token = sess.admit_pending
            # spec_hist was extended in place at admission (and by every
            # accepted token since); restore the saved copy so a retry of
            # the same prompt doesn't duplicate its region in the drafting
            # corpus and tank the lookup accept rate
            sess.spec_hist = list(sess.admit_spec_hist)
            sess.last_used = time.monotonic()
            sess.lane = None
            self.sessions[sess.name] = -1
            self._truncate_session_pages(sess)
        slot.session = ""
        # the session is provably idle right now: stage any snapshot that
        # parked while the failed request was in flight (mirrors the finish
        # path's _service_parked_snapshot — without this the parked cmd's
        # future never resolves and the serve layer awaits it forever)
        cmd = self._snap_parked.pop(sess.name, None)
        if cmd is not None:
            self._stage_snapshot_paged(cmd, sess)

    def _drop_lane_session(self, slot: Slot) -> None:
        """Paged half of a lane reset after a FAULT/abort: the bound
        session's KV is no longer trusted (the failed call may have died
        mid-write), so its pages go back to the pool and the session
        leaves residency entirely (the store snapshot still allows resume)."""
        with self._page_lock:
            sess, slot.psess = slot.psess, None
            self._bt[slot.idx, :] = self._scratch_page(slot.idx)
            if sess is None:
                return
            self._free_session_pages(sess)
            if sess.name:
                self.paged_sessions.pop(sess.name, None)
                self.sessions.pop(sess.name, None)
                self._flush_parked_snapshot(sess.name)
            slot.session = ""

    def _detach_lane(self, slot: Slot) -> None:
        """A finished request releases its COMPUTE lane while the session
        stays resident in pages — the decoupling that lets resident
        sessions outnumber max_batch. Lane spec/position state syncs back
        to the session; anonymous (sessionless) generations free their
        pages immediately."""
        with self._page_lock:
            sess, slot.psess = slot.psess, None
            self._bt[slot.idx, :] = self._scratch_page(slot.idx)
            if sess is None:
                return
            sess.spec_ema = slot.spec_ema
            sess.spec_miss = slot.spec_miss
            sess.last_used = time.monotonic()
            sess.lane = None
            if sess.name:
                self.sessions[sess.name] = -1
                self._truncate_session_pages(sess)
            else:
                self._free_session_pages(sess)
        slot.session = ""
        slot.position = 0
        slot.pending_token = None
        slot.spec_hist = []

    # paged compiled helpers: exact-page-count gather/scatter programs.
    # Counts are bounded by the block-table width (≤ max_seq/page_size
    # distinct shapes, each a trivial gather), warmed at pow2 counts.

    def _snap_fn_paged(self, count: int):
        fn = self._snap_paged_fns.get(count)
        if fn is None:

            def _snap(cache, ids, _c=count):
                # EXACT dtype (see _snap_fn): gather ONLY the session's
                # live pages and lay them out contiguously — the blob
                # layout matches the dense staging, so snapshots restore
                # across paged and dense engines alike
                k = cache.k[:, ids]
                v = cache.v[:, ids]
                l = cache.k.shape[0]
                return (
                    k.reshape(l, _c * self.page_size, *cache.k.shape[3:]),
                    v.reshape(l, _c * self.page_size, *cache.v.shape[3:]),
                )

            fn = self._snap_paged_fns[count] = jax.jit(_snap)
        return fn

    def _restore_fn_paged(self, count: int):
        fn = self._restore_paged_fns.get(count)
        if fn is None:

            def _restore(cache, ids, k, v):
                # k/v arrive [L, count, page_size, KV, hd]; scatter into
                # the session's freshly-allocated pages
                return type(cache)(
                    cache.k.at[:, ids].set(k), cache.v.at[:, ids].set(v)
                )

            fn = self._restore_paged_fns[count] = jax.jit(
                _restore, donate_argnums=(0,)
            )
        return fn

    def _page_copy_fn(self):
        """One-page pool copy (src → dst): the partial-tail copy-on-write
        for non-page-aligned prefix levels. Full pages are never copied —
        that is the zero-copy claim."""
        fn = self._page_copy_fn_cached
        if fn is None:

            def _copy(cache, src, dst):
                k = lax.dynamic_slice_in_dim(cache.k, src, 1, axis=1)
                v = lax.dynamic_slice_in_dim(cache.v, src, 1, axis=1)
                return type(cache)(
                    lax.dynamic_update_slice_in_dim(cache.k, k, dst, axis=1),
                    lax.dynamic_update_slice_in_dim(cache.v, v, dst, axis=1),
                )

            fn = self._page_copy_fn_cached = jax.jit(_copy, donate_argnums=(0,))
        return fn

    # -- prefix arena (cross-session KV reuse; worker thread) -------------
    @staticmethod
    def _rolling_hashes(tokens: list[int]) -> dict[int, int]:
        """FNV-1a rolling hash of the token-id stream, sampled at every
        prefill-bucket boundary: hashes[b] keys the exact prefix tokens[:b].
        One O(len) pass per admission/registration — the same order of work
        as tokenizing the prompt."""
        h = 1469598103934665603
        out: dict[int, int] = {}
        bi = 0
        for i, t in enumerate(tokens):
            h = ((h ^ (int(t) + 1)) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
            if bi < len(PREFILL_BUCKETS) and i + 1 == PREFILL_BUCKETS[bi]:
                out[PREFILL_BUCKETS[bi]] = h
                bi += 1
        return out

    def _prefix_slice_fn(self, bucket: int):
        """Copy a slot's first ``bucket`` KV positions into FRESH device
        buffers (one compiled program per bucket, like _snap_fn). The
        outputs are independent arrays, so they survive every later
        donation of the main cache. No dtype cast: a forked prefix must be
        bit-exact with the prefill that produced it."""
        fn = self._prefix_slice_fns.get(bucket)
        if fn is None:

            def _slice(cache, i, _b=bucket):
                k = lax.dynamic_slice_in_dim(cache.k, i, 1, axis=1)[:, 0, :_b]
                v = lax.dynamic_slice_in_dim(cache.v, i, 1, axis=1)[:, 0, :_b]
                return k, v

            fn = self._prefix_slice_fns[bucket] = jax.jit(_slice)
        return fn

    def _prefix_fork_fn(self, bucket: int):
        """Write an arena entry into a slot's rows at position 0 (the
        admission-time fork). Donates the cache — in-place on device; the
        entry buffers are NOT donated, so the arena can fork the same
        prefix into any number of later sessions."""
        fn = self._prefix_fork_fns.get(bucket)
        if fn is None:

            def _fork(cache, i, k, v):
                newk = lax.dynamic_update_slice(cache.k, k[:, None], (0, i, 0, 0, 0))
                newv = lax.dynamic_update_slice(cache.v, v[:, None], (0, i, 0, 0, 0))
                return KVCache(newk, newv)

            fn = self._prefix_fork_fns[bucket] = jax.jit(_fork, donate_argnums=(0,))
        return fn

    def _prefix_lookup(self, prompt: list[int]):
        """Longest cached prefix at bucket granularity, or None. A hit must
        leave at least one prompt token to prefill (the first generated
        token is sampled from prefill logits). Hash match is verified by
        exact token equality — a collision degrades to a miss."""
        limit = len(prompt) - 1
        hashes = self._rolling_hashes(prompt)
        for b in reversed(self._prefix_levels):
            if b > limit:
                continue
            key = (b, hashes.get(b))
            entry = self._prefix_entries.get(key)
            if entry is not None and entry.tokens == tuple(prompt[:b]):
                return key, entry
        return None

    def _prefix_register(self, slot: Slot) -> None:
        """Final-prefill-chunk hook: store every bucket-level prefix of a
        fresh-context prompt that isn't cached yet. Each level is one
        async device copy; positions [0:b] hold real KV for exactly
        ctx[:b] by causality (later tokens cannot influence them).
        Best-effort — a failure here must never fail the generation."""
        ctx = slot.prefix_ctx
        slot.prefix_ctx = None
        if ctx is None or not self._prefix_active:
            return
        n = min(len(ctx), slot.position)
        try:
            hashes = self._rolling_hashes(ctx)
            now = time.monotonic()
            for b in self._prefix_levels:
                if b > n:
                    break
                key = (b, hashes[b])
                if key in self._prefix_entries:
                    continue
                if self.paged:
                    if not self._prefix_register_paged(slot, ctx, b, key, now):
                        break
                    continue
                k, v = self._prefix_slice_fn(b)(self.cache, jnp.int32(slot.idx))
                nbytes = int(k.nbytes + v.nbytes)
                if nbytes > self._prefix_budget:
                    break  # larger levels only grow — stop here
                while (
                    self._prefix_bytes + nbytes > self._prefix_budget
                    and self._prefix_entries
                ):
                    self._prefix_evict_lru(now)
                self._prefix_entries[key] = PrefixEntry(
                    k=k,
                    v=v,
                    tokens=tuple(ctx[:b]),
                    nbytes=nbytes,
                    created=now,
                    last_used=now,
                )
                self._prefix_bytes += nbytes
        except Exception as e:
            self._note_error(e)

    def _prefix_register_paged(
        self, slot: Slot, ctx: list[int], b: int, key: tuple, now: float
    ) -> bool:
        """Zero-copy paged registration: pin the owning session's full
        pages below ``b`` by refcount — no device copy at all for
        page-aligned levels. A non-aligned level (bucket 32 under the
        64-token default page) eagerly copies its partial tail page once,
        because the owner keeps writing the rest of that page. Returns
        False to stop the level walk (budget exhausted)."""
        sess = slot.psess
        if sess is None:
            return False
        full = b // self.page_size
        tail_len = b % self.page_size
        page_bytes = self._page_nbytes()
        nbytes = (full + (1 if tail_len else 0)) * page_bytes
        if nbytes > self._prefix_budget:
            return False
        if len(sess.pages) < full + (1 if tail_len else 0):
            return False  # context shorter than the level (can't happen)
        # budget charge is the DISTINCT pinned page count: levels of one
        # context share their full pages, so summing per-entry spans (the
        # dense formula, where every level is a real private copy) would
        # double-count and stop registration far short of the budget
        full_pages = sess.pages[:full]

        def projected() -> int:
            pinned = self._prefix_pinned_page_ids()
            extra = sum(1 for p in full_pages if p not in pinned)
            return (len(pinned) + extra + (1 if tail_len else 0)) * page_bytes

        while projected() > self._prefix_budget and self._prefix_entries:
            self._prefix_evict_lru(now)
        tail_page = None
        if tail_len:
            # best-effort, no reclaim: pinning a prefix must never evict a
            # live resident session, and a full pool just stops the level
            # walk — registration is an optimization, not backpressure
            try:
                tail_page = self._alloc_pages(1, serving=False, reclaim=False)[0]
            except EngineOverloaded:
                return False
            self.cache = self._page_copy_fn()(
                self.cache, jnp.int32(sess.pages[full]), jnp.int32(tail_page)
            )
        pages = list(sess.pages[:full])
        with self._page_lock:
            for pid in pages:
                self._page_refs[pid] += 1
        self._prefix_entries[key] = PrefixEntry(
            k=None,
            v=None,
            tokens=tuple(ctx[:b]),
            nbytes=nbytes,
            created=now,
            last_used=now,
            pages=pages,
            tail_page=tail_page,
            tail_len=tail_len,
        )
        self._recount_prefix_pinned()
        return True

    def _page_nbytes(self) -> int:
        return int((self.cache.k.nbytes + self.cache.v.nbytes) / self._total_pages)

    def _prefix_pinned_page_ids(self) -> set[int]:
        """Distinct physical pages pinned by the paged prefix arena —
        levels of one context share pages, so per-entry spans overlap."""
        pinned: set[int] = set()
        for e in self._prefix_entries.values():
            if e.pages is not None:
                pinned.update(e.pages)
                if e.tail_page is not None:
                    pinned.add(e.tail_page)
        return pinned

    def _recount_prefix_pinned(self) -> None:
        self._prefix_bytes = len(self._prefix_pinned_page_ids()) * self._page_nbytes()

    def _prefix_evict_lru(self, now: float | None = None) -> None:
        key, entry = self._prefix_entries.popitem(last=False)
        self._prefix_bytes -= entry.nbytes
        if entry.pages is not None:
            # unpin: sessions still mapping these pages keep their own
            # references — only the arena's pin drops
            for pid in entry.pages:
                self._decref_page(pid)
            if entry.tail_page is not None:
                self._decref_page(entry.tail_page)
            # distinct-page accounting: surviving entries may still pin
            # pages this entry shared, so recount instead of subtracting
            self._recount_prefix_pinned()
        self._count_eviction(
            "prefix", (now or time.monotonic()) - entry.last_used
        )

    def _count_eviction(self, kind: str, idle_s: float) -> None:
        """Shared eviction counter path (session slots AND prefix arena):
        a prefix hit-rate regression is diagnosed by which pool churns."""
        if kind == "session":
            self.session_evictions += 1
            self.session_eviction_idle_s_recent.append(idle_s)
        else:
            self.prefix_evictions += 1
            self.prefix_eviction_idle_s_recent.append(idle_s)

    async def restore_session(self, session: str, blob: bytes) -> bool:
        """Load a snapshot into a fresh slot (worker-thread mediated)."""
        from .checkpoint import deserialize_kv_slot

        k, v, header = deserialize_kv_slot(blob)
        loop = asyncio.get_running_loop()
        cmd = RestoreCmd(
            session=session,
            k=k,
            v=v,
            position=int(header["position"]),
            pending_token=header.get("pending_token"),
            loop=loop,
            future=loop.create_future(),
        )
        self._queue.put(cmd)
        return await cmd.future

    def clear_sessions(self, prefix: str = "") -> None:
        """Drop idle sessions (all, or only those whose name starts with
        ``prefix`` — a multi-tenant host clears one tenant's namespace
        without touching its co-tenants' KV)."""
        if self.kv_tiering:
            # host-tier entries are sessions too: clearing must not leave
            # a parked copy that the next same-named session promotes
            with self._tier_lock:
                for name in [s for s in self._host_tier if s.startswith(prefix)]:
                    self._tier_drop_locked(name)
        if self.paged:
            with self._page_lock:
                for name in [s for s in self.paged_sessions if s.startswith(prefix)]:
                    sess = self.paged_sessions[name]
                    if sess.lane is not None:
                        continue  # request in flight; same skip as dense
                    self._flush_parked_snapshot(name)
                    self._free_session_pages(sess)
                    self.paged_sessions.pop(name, None)
                    self.sessions.pop(name, None)
            return
        with self._lock:
            for name in [s for s in self.sessions if s.startswith(prefix)]:
                idx = self.sessions.pop(name)
                self._flush_parked_snapshot(name)
                slot = self.slots[idx]
                if slot.request is None:
                    slot.session = ""
                    slot.position = 0
                    slot.epoch += 1

    def metrics(self) -> dict:
        elapsed = max(1e-6, time.monotonic() - self._started_at)
        recent = sorted(self.ttft_ms_recent)
        itl = sorted(self.itl_ms_recent)
        adm = sorted(self.admission_ms_recent)
        pre = sorted(self.prefill_ms_recent)
        frb = sorted(self.first_readback_ms_recent)
        return {
            "tokens_generated": self.tokens_generated,
            "tokens_per_s": round(self.tokens_generated / elapsed, 2),
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "batch_occupancy": round(self._occupancy_sum / max(1, self.decode_steps), 3),
            "ttft_ms_p50": round(recent[len(recent) // 2], 2) if recent else None,
            "itl_ms_p50": round(itl[len(itl) // 2], 2) if itl else None,
            # TTFT phase decomposition: queue-wait (admission_ms, submit →
            # first prefill chunk dispatched) + prefill (first chunk →
            # first-token injection) + first-readback (injection → token on
            # host) ≈ ttft_ms per request
            "admission_ms_p50": round(adm[len(adm) // 2], 2) if adm else None,
            "admission_ms_max": round(adm[-1], 2) if adm else None,
            "admission_samples": [round(x, 2) for x in self.admission_ms_recent],
            "ttft_prefill_ms_p50": round(pre[len(pre) // 2], 2) if pre else None,
            "ttft_first_readback_ms_p50": round(frb[len(frb) // 2], 2) if frb else None,
            "ttft_prefill_samples": [round(x, 2) for x in self.prefill_ms_recent],
            "ttft_first_readback_samples": [
                round(x, 2) for x in self.first_readback_ms_recent
            ],
            # adaptive decode-chunk policy: configured chunk, dispatched
            # chunk-size histogram, and how often contention shrank it
            "decode_chunk": self.decode_chunk,
            "adaptive_decode": self.adaptive_decode,
            # .copy() first: the worker thread inserts a NEW key on the
            # first dispatch of each chunk size — iterating the live dict
            # from the metrics thread could raise mid-scrape
            "decode_chunk_hist": {
                str(k): v for k, v in sorted(self.decode_chunk_hist.copy().items())
            },
            "decode_chunks_shrunk": self.decode_chunks_shrunk,
            # self-speculative decoding: drafted/accepted/rejected token
            # counters, verify-bucket histogram (.copy() for the same
            # mid-scrape reason as decode_chunk_hist), and each slot's live
            # acceptance EMA — a collapsed gamma shows up as EMAs pinned
            # under the floor while spec_rounds stops advancing
            "speculative": self.speculative,
            "spec_rounds": self.spec_rounds,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_rejected": self.spec_rejected,
            "spec_acceptance_rate": (
                round(self.spec_accepted / self.spec_drafted, 4)
                if self.spec_drafted
                else None
            ),
            "spec_verify_hist": {
                str(k): v for k, v in sorted(self.spec_verify_hist.copy().items())
            },
            "spec_slot_acceptance": [round(s.spec_ema, 3) for s in self.slots],
            # fused on-device decode loop: loops dispatched, device steps
            # executed (early exits run fewer than the rung), early-exit
            # count, exit-reason histogram, and the host-sync economics —
            # host_syncs_per_token is THE fused-vs-unfused readback claim
            # as a gauge (one sync per loop exit vs one per chunk, plus
            # the shared first-token and spec-round syncs in both modes)
            "fused_decode": self.fused_decode,
            "fused_loops_total": self.fused_loops_total,
            "fused_steps_total": self.fused_steps_total,
            "fused_early_exits_total": self.fused_early_exits_total,
            "fused_exit_reason_hist": dict(
                sorted(self.fused_exit_reason_hist.copy().items())
            ),
            # ISSUE 17: double-buffered lane injection (staged absorbs vs
            # exit-and-redispatch fallbacks) and in-loop n-gram speculation
            # (device-counted drafted/accepted, read back in the packed
            # loop transfer — no extra syncs)
            "fused_injections_total": self.fused_injections_total,
            "fused_inject_fallbacks_total": self.fused_inject_fallbacks_total,
            "inloop_spec": self.inloop_spec,
            "inloop_spec_drafted": self.inloop_spec_drafted,
            "inloop_spec_accepted": self.inloop_spec_accepted,
            "inloop_spec_acceptance_rate": (
                round(self.inloop_spec_accepted / self.inloop_spec_drafted, 4)
                if self.inloop_spec_drafted
                else None
            ),
            "approx_topk": self.approx_topk,
            "host_syncs_total": self.host_syncs_total,
            "host_syncs_per_token": (
                round(self.host_syncs_total / self.tokens_generated, 4)
                if self.tokens_generated
                else None
            ),
            "worker_errors": self.worker_errors,
            "last_worker_error": self.last_worker_error or None,
            "cache_resets": self.cache_resets,
            # request-lifecycle policy plane: deadlines/cancel/shed state.
            # queue_depth/waiting_depth/active_requests are the admission
            # picture the control plane's shedding watermark reads.
            "deadlines": self.deadlines,
            "queue_depth": self._queue.qsize(),
            "waiting_depth": len(self._waiting),
            "active_requests": sum(1 for s in self.slots if s.request is not None),
            "cancelled_total": self.cancelled_total,
            "expired_total": self.expired_total,
            "shed_total": self.shed_total,
            "shed_watermark": self.shed_watermark or None,
            "draining": self._draining,
            # prefix arena (cross-session KV reuse): hit/miss/saved counters
            # plus occupancy — tokens_saved is prefill work the fork skipped
            "prefix_cache": self.prefix_cache,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "prefix_arena_entries": len(self._prefix_entries),
            "prefix_arena_bytes": self._prefix_bytes,
            "prefix_arena_capacity_bytes": self._prefix_budget,
            "prefix_evictions_total": self.prefix_evictions,
            # session-slot LRU eviction (was silent): count + idle age of
            # the evictees, so "why did my session re-prefill" is answerable
            "session_evictions_total": self.session_evictions,
            "session_eviction_idle_s_p50": (
                round(sev[len(sev) // 2], 2)
                if (sev := sorted(self.session_eviction_idle_s_recent))
                else None
            ),
            "prefix_eviction_idle_s_p50": (
                round(pev[len(pev) // 2], 2)
                if (pev := sorted(self.prefix_eviction_idle_s_recent))
                else None
            ),
            # paged KV arena (block tables): pool occupancy gauges replace
            # the dense-only slot accounting as the HBM audit — resident
            # sessions are bounded by pages, not max_batch, so capacity
            # questions are answered here, not by active_sessions alone
            **self._paged_metrics(),
            # tiered KV hierarchy: per-tier session counts, host-tier
            # bytes/quantized pages, demote/promote/prewarm totals, and the
            # promote-overlap hidden-ms — the capacity claim's gauges
            **self._tier_metrics(),
            # raw append-ordered samples (bounded deques): lets a caller
            # window percentiles over ITS measurement interval instead of
            # whatever warmup/compile history the deque still holds
            "ttft_samples": [round(x, 2) for x in self.ttft_ms_recent],
            "itl_samples": [round(x, 2) for x in self.itl_ms_recent],
            "active_sessions": len(self.sessions),
            "max_batch": self.max_batch,
            "max_seq": self.max_seq,
            "tp": self.tp,
            "ep": self.ep,
            "sp": self.sp,
            "meshed_flash": self.meshed_flash,
            "moe_routed": self.routed_moe,
            # decode-sized routed calls (t == 1) run dropless via the
            # call-shape gate in models/llama._moe_mlp_routed for ANY
            # max_batch (ADVICE r5: the old n<=64 gate silently reverted
            # engines with max_batch > 64 to cf-capped routing) — only
            # prefill can drop, bounded by the capacity factor
            "moe_decode_dropless": self.routed_moe or None,
            "moe_capacity_factor": self.moe_capacity_factor if self.routed_moe else None,
            # FLOP model + HBM telemetry: lifetime MFU here is a floor
            # (includes idle time); bench_llm.py samples flops_done twice
            # and computes windowed MFU over the loaded interval
            "flops_done": self.flops_done,
            "mfu_lifetime": round(self.flops_done / elapsed / self._peak_flops, 5),
            "hbm_bytes_read": self.hbm_bytes_read,
            "mbu_lifetime": round(self.hbm_bytes_read / elapsed / self._peak_hbm_bps, 5),
            "hbm_gbps_peak": round(self._peak_hbm_bps / 1e9, 1),
            "peak_tflops": round(self._peak_flops / 1e12, 1),
            "chip_kind": self._chip.kind,
            "n_chips": self._n_chips,
            "param_hbm_bytes": self.param_hbm_bytes,
            "kv_arena_bytes": self.kv_arena_bytes,
            "hbm_bytes_per_chip_est": int(
                (self.param_hbm_bytes + self.kv_arena_bytes) / self._n_chips
            ),
        }

    def _paged_metrics(self) -> dict:
        if not self.paged:
            return {"paged_kv": False}
        with self._page_lock:
            free = len(self._page_free)
            quarantined = len(self._page_quarantine)
            per_sess = sorted(
                len(s.pages) for s in self.paged_sessions.values()
            )
            live_tokens = sum(s.position for s in self.paged_sessions.values())
            pinned = len(self._prefix_pinned_page_ids())
        allocated = sum(per_sess)
        # internal fragmentation: allocated page capacity the resident
        # sessions' live tokens don't fill (the cost of page granularity —
        # dense slots score (1 - position/max_seq) on the same formula)
        frag = (
            round(100.0 * (1.0 - live_tokens / (allocated * self.page_size)), 2)
            if allocated
            else 0.0
        )
        return {
            "paged_kv": True,
            "page_size": self.page_size,
            "kv_pages_total": self._data_pages,
            "kv_pages_free": free,
            "kv_pages_used": self._data_pages - free - quarantined,
            "kv_pages_quarantined": quarantined,
            "kv_pages_prefix_pinned": pinned,
            "resident_sessions": len(self.paged_sessions),
            "session_pages_p50": per_sess[len(per_sess) // 2] if per_sess else None,
            "session_pages_max": per_sess[-1] if per_sess else None,
            "kv_fragmentation_pct": frag,
            "page_exhausted_total": self.page_exhausted_total,
            "pages_truncated_total": self.pages_truncated,
            "prefix_pages_shared_total": self.prefix_pages_shared,
        }

    def begin_drain(self) -> None:
        """Stop admitting (generate() raises EngineDraining); in-flight and
        already-queued work keeps running. First half of graceful SIGTERM."""
        self._draining = True

    def drain(self, budget_s: float = 10.0) -> bool:
        """Block until every queued/waiting/in-flight request settles, up to
        ``budget_s``; returns True on a clean drain. Called off the worker
        thread (serve-layer cleanup). Work still live when the budget runs
        out is failed by the caller's subsequent shutdown()."""
        self.begin_drain()

        def busy() -> bool:
            return bool(
                any(s.request is not None for s in self.slots)
                or self._waiting
                or not self._queue.empty()
                or self._readbacks
            )

        deadline = time.monotonic() + max(0.0, budget_s)
        while time.monotonic() < deadline:
            if not busy():
                return True
            time.sleep(0.05)
        # same predicate at the budget's edge: queued/waiting leftovers the
        # subsequent shutdown() will fail must not report drained_clean
        return not busy()

    def shutdown(self) -> None:
        self._running = False
        self._queue.put(None)
        self._worker.join(timeout=10)
        # one more drain after the join: items enqueued after the worker's
        # own exit drain (or left behind by a crashed worker) must fail,
        # not hang their callers forever (ADVICE r5)
        self._fail_pending(EngineShutdown("engine shut down"))
        for session in list(self._snap_parked):
            self._flush_parked_snapshot(session)

    # -- worker thread ----------------------------------------------------
    #
    # Pipelined decode (round-3 perf work): the device carry chains decode
    # chunks with no host round-trip between them; token readbacks are
    # initiated asynchronously at dispatch and PROCESSED one pipeline slot
    # later, so the axon/PCIe readback RTT rides under the next chunk's
    # compute instead of serializing with it. Consequences the logic below
    # accounts for: EOS/finish detection lags by up to one chunk (the extra
    # lane-steps are parked garbage, overwritten before any query can attend
    # to them), and a finished lane keeps decoding until its park-injection
    # lands (clamped at the scratch position).
    _PIPELINE_DEPTH = 1  # readback RTT < chunk compute, so depth 1 hides it

    def _loop(self) -> None:
        while self._running and not self._sentinel:
            busy = any(s.request is not None for s in self.slots) or bool(self._readbacks)
            self._pump_queue(0.0 if (busy or self._waiting) else 0.2)
            if self._sentinel:
                break
            if self.paged:
                # freed pages parked behind in-flight dispatches become
                # allocatable once the readback FIFO has drained
                self._release_quarantine()
            self._admit_waiting()
            # cancelled/expired in-flight lanes are reaped BEFORE dispatching
            # more device work for them; their freed slots are admissible on
            # the next iteration's _admit_waiting pass
            self._reap_aborted()
            # ONE prefill chunk, then a decode chunk: a long prompt is fed
            # through chunk-by-chunk between decode chunks, so admitting it
            # never stalls active generations for more than one chunk's
            # latency. When NOTHING is decoding, prefill multi-ticks back to
            # back instead — a cold 1024-token prompt must not pay a full
            # worker iteration of decode-dispatch bookkeeping per 256-token
            # chunk. Prefill faults are PER-REQUEST: the culprit request
            # fails, everyone else keeps decoding (VERDICT r4 item 1b — a
            # single poisoned prompt used to fail every in-flight request).
            try:
                self._prefill_tick()
                while self.adaptive_decode and not any(
                    s.decoding for s in self.slots
                ) and any(
                    s.request is not None and s.pending_prompt for s in self.slots
                ):
                    # keep admitting between chunks: a newcomer's first
                    # chunk outranks an in-progress prompt's next chunk
                    # (admission-first ordering in _prefill_tick)
                    self._pump_queue(0.0)
                    if self._sentinel:
                        break
                    self._admit_waiting()
                    self._prefill_tick()
            except Exception as e:
                self._note_error(e)
                slot = self._prefilling_slot
                if slot is not None and slot.request is not None:
                    self._fail_item(slot.request, _as_prefill_failure(e))
                    self._reset_slot(slot)
                self._ensure_device_state()
            finally:
                self._prefilling_slot = None
            try:
                if any(s.decoding for s in self.slots):
                    # speculative verify round when lanes have drafts;
                    # otherwise (or under contention) the plain pipelined
                    # decode-chunk path — gamma collapse makes low-match
                    # traffic live here permanently. With in-loop spec the
                    # drafter/verifier run INSIDE the fused loop body, so
                    # the host-side round-trip is skipped entirely.
                    if self.inloop_spec or not self._try_speculate():
                        if self.fused_decode:
                            self._fused_dispatch()
                        else:
                            self._decode_dispatch()
                else:
                    self._last_decode_end = None  # idle gap isn't ITL
                # drain landed readbacks; block on the oldest when the
                # pipeline is full (that wait IS the backpressure bounding
                # how far dispatch runs ahead of the device) or when there
                # is nothing else worth dispatching (lanes whose whole token
                # budget is already in flight don't count — dispatching more
                # would burn a garbage chunk just to have something to do)
                self._drain_readbacks(
                    block=len(self._readbacks) > self._PIPELINE_DEPTH
                    or not self._has_dispatchable()
                )
            except Exception as e:
                # a decode/readback fault is batch-wide by construction (one
                # compiled call covers every lane): fail the in-flight
                # requests, then verify the donated device state survived —
                # if not, reallocate so the engine serves on, sessions cold
                self._note_error(e)
                for slot in self.slots:
                    if slot.request is not None:
                        self._fail_item(slot.request, e)
                        self._reset_slot(slot)
                self._readbacks.clear()
                self._ensure_device_state()
            if not any(s.request is not None for s in self.slots) and self._waiting:
                time.sleep(0.002)  # all slots busy-by-session; brief backoff
        # worker exit: nothing may hang on a dead worker — fail queued work,
        # drained-but-unadmitted work, and in-flight requests (ADVICE r5:
        # the None sentinel used to abandon SnapshotCmd/RestoreCmd/
        # GenRequest futures forever)
        self._fail_pending(EngineShutdown("engine shut down"))

    def _pump_queue(self, block_s: float) -> None:
        """Drain the submit queue into the waiting list (a burst admits
        together). The shutdown sentinel sets ``_sentinel`` instead of
        returning mid-drain so every caller unwinds to the exit drain."""
        try:
            if block_s > 0:
                item = self._queue.get(timeout=block_s)
            else:
                item = self._queue.get_nowait()
            while True:
                if item is None:
                    self._sentinel = True
                    return
                self._waiting.append(item)
                item = self._queue.get_nowait()
        except queue.Empty:
            pass

    def _admit_waiting(self) -> None:
        still = []
        for item in self._waiting:
            try:
                if isinstance(item, RestoreCmd):
                    self._do_restore(item)
                elif isinstance(item, SnapshotCmd):
                    self._do_snapshot(item)
                elif isinstance(item, ParkCmd):
                    self._do_park(item)
                elif isinstance(item, PrewarmCmd):
                    self._do_prewarm(item)
                elif self._pre_reject(item):
                    pass  # expired/cancelled before prefill — already failed
                elif self._tier_needs_promote(item) and not self._tier_promote(
                    item.session
                ):
                    # host-parked session whose device swap-in failed
                    # (injected kv_promote fault or pool pressure): typed
                    # backpressure — the entry stays parked, a retry finds
                    # the session still promotable
                    raise TierPromoteFailed(item.session)
                elif not self._try_admit(item):
                    still.append(item)
            except EngineOverloaded as e:
                # pool backpressure at admission (the prefix tail-CoW
                # alloc): a policy 429, not a worker fault — fail typed
                # without polluting the worker-error channel, matching the
                # prefill/decode exhaustion handlers
                self._fail_item(item, e)
            except Exception as e:
                # a poisoned request/snapshot must not kill the worker
                self._note_error(e)
                self._fail_item(item, e)
        self._waiting = still

    def _take_cancel(self, request_id: str) -> bool:
        with self._lock:
            return self._cancel_requested.pop(request_id, None) is not None

    def _purge_stale_cancels(self) -> None:
        """Drop cancel markers whose request never showed up (TTL): the
        client-disconnect path can record a cancel for a dispatch that died
        on the wire before the engine saw it."""
        if not self._cancel_requested:
            return
        cutoff = time.monotonic() - self._cancel_ttl_s
        with self._lock:
            for rid in [r for r, t in self._cancel_requested.items() if t < cutoff]:
                del self._cancel_requested[rid]

    def _pre_reject(self, req: GenRequest) -> bool:
        """Fail a not-yet-admitted request whose caller is gone: cancelled
        ids and past-deadline arrivals never reach prefill — the whole point
        of the admission-side check is that a deadline miss costs ZERO
        device work."""
        if self._take_cancel(req.id):
            self.cancelled_total += 1
            self._fail_item(req, RequestCancelled(f"request {req.id} cancelled"))
            return True
        if self.deadlines and req.deadline_at is not None and time.time() > req.deadline_at:
            self.expired_total += 1
            self._fail_item(
                req, RequestExpired(f"request {req.id} deadline exceeded before prefill")
            )
            return True
        return False

    def _reap_aborted(self) -> None:
        """Per-iteration sweep of in-flight lanes: a cancelled request (or
        one whose deadline passed mid-generation) is parked mid-decode and
        its slot freed for admission — decoding on for a caller that is gone
        is pure waste under overload. In-flight readback entries for the
        reaped request are skipped at processing (request-identity check),
        the same staleness discipline finished lanes already use."""
        self._purge_stale_cancels()
        if not self._cancel_requested and not (
            self.deadlines
            and any(
                s.request is not None and s.request.deadline_at is not None
                for s in self.slots
            )
        ):
            return
        now = time.time()
        for slot in self.slots:
            req = slot.request
            if req is None:
                continue
            if self._take_cancel(req.id):
                self.cancelled_total += 1
                err: Exception = RequestCancelled(f"request {req.id} cancelled mid-flight")
            elif (
                self.deadlines
                and req.deadline_at is not None
                and now > req.deadline_at
            ):
                self.expired_total += 1
                err = RequestExpired(f"request {req.id} deadline exceeded mid-flight")
            else:
                continue
            self._fail_item(req, err)
            self._abandon_slot(slot)

    def _inject_lane(
        self, idx: int, first, position: int, temp: float, top_k: int, top_p: float,
        hist_row=None, hist_n: int = 0,
    ) -> None:
        """Jitted single-lane scatter into the 7-array decode carry (token,
        position, temperature, top_k, top_p, spec history, history length).
        ``hist_row`` seeds the in-loop drafter with the prompt tail (host
        int32 [FUSED_HIST_W], left-shifted in the scatter so ``first``
        lands in the newest slot); None parks the history empty."""
        if hist_row is None:
            hist_row = jnp.zeros((FUSED_HIST_W,), jnp.int32)
        (
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            self._dhist,
            self._dhlen,
        ) = self._inject(
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            self._dhist,
            self._dhlen,
            jnp.int32(idx),
            first,
            jnp.int32(position),
            jnp.float32(temp),
            jnp.int32(top_k),
            jnp.float32(top_p),
            hist_row,
            jnp.int32(hist_n),
        )

    def _stage_lane(
        self, idx: int, first, position: int, temp: float, top_k: int, top_p: float,
        hist_row=None, hist_n: int = 0,
    ) -> None:
        """Write a freshly prefilled lane into the STAGING shadow carry
        instead of the live one: the already-dispatched fused loop absorbs
        it at entry via the ``armed`` flag (double-buffered injection) —
        continuous batching without exiting the running loop. Same jitted
        scatter as ``_inject_lane``, pointed at the shadow arrays."""
        if hist_row is None:
            hist_row = jnp.zeros((FUSED_HIST_W,), jnp.int32)
        (
            self._stok,
            self._spos,
            self._stemps,
            self._stopk,
            self._stopp,
            self._shist,
            self._shlen,
        ) = self._inject(
            self._stok,
            self._spos,
            self._stemps,
            self._stopk,
            self._stopp,
            self._shist,
            self._shlen,
            jnp.int32(idx),
            first,
            jnp.int32(position),
            jnp.float32(temp),
            jnp.int32(top_k),
            jnp.float32(top_p),
            hist_row,
            jnp.int32(hist_n),
        )

    def _park_lane(self, idx: int) -> None:
        """Point a lane at the scratch position with neutral sampling state
        (idle/finished/aborted lanes all park identically)."""
        self._inject_lane(idx, jnp.int32(0), self.scratch_pos, 0.0, 0, 1.0)
        if self._staged_lane == idx:
            # a staged-but-not-yet-absorbed lane that gets parked (abort
            # between staging and dispatch) must not arm into the next loop
            self._staged_lane = None

    def _abandon_slot(self, slot: Slot, rollback: bool = False) -> None:
        """Free a slot whose request was aborted mid-flight: park its decode
        lane (chunks already dispatched keep stepping it until the park
        injection lands, their tokens skipped at processing), then return
        the slot to cold idle — the KV prefix holds a partial generation the
        session's recorded history will never contain, so continuing from it
        would desync context."""
        if slot.decoding:
            slot.decoding = False
            slot.dev_position = self.scratch_pos
            self._park_lane(slot.idx)
        self._reset_slot(slot, rollback=rollback)

    def _has_dispatchable(self) -> bool:
        """Is there device work left to dispatch? Pending prompt chunks, or
        a decoding lane with token budget not yet in flight."""
        for s in self.slots:
            if s.request is None:
                continue
            if s.pending_prompt:
                return True
            if s.decoding and s.request.dispatched < s.request.max_tokens:
                return True
        return False

    def _fail_pending(self, error: Exception) -> None:
        """Fail everything still owed a result: waiting items, queued items,
        and in-flight slot requests. Called from the worker's exit path and
        again from shutdown() after the join (late enqueues)."""
        for item in self._waiting:
            self._fail_item(item, error)
        self._waiting = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._fail_item(item, error)
        for slot in self.slots:
            if slot.request is not None:
                self._fail_item(slot.request, error)
                slot.request = None
                slot.pending_prompt = []
                slot.decoding = False

    def _note_error(self, e: Exception) -> None:
        self.worker_errors += 1
        self.last_worker_error = f"{type(e).__name__}: {e}"
        print(f"[llm-engine] worker error: {self.last_worker_error}", flush=True)

    def _reset_slot(self, slot: Slot, rollback: bool = False) -> None:
        """Return a slot to cold idle after its request failed: KV prefix is
        no longer trusted (the fault may have landed mid-write). With
        ``rollback`` (policy failures: pool exhaustion — the alloc fails
        BEFORE any dispatch) the paged session's pre-request KV is trusted
        and preserved instead."""
        if self.paged:
            if rollback:
                self._rollback_lane_session(slot)
            else:
                self._drop_lane_session(slot)
        if self._staged_lane == slot.idx:
            # staged-but-unabsorbed lane dying on a fault path must not arm
            # its stale shadow state into the next fused loop
            self._staged_lane = None
        slot.request = None
        slot.pending_prompt = []
        slot.decoding = False
        slot.position = 0
        slot.pending_token = None
        slot.prefix_ctx = None
        slot.spec_hist = []
        slot.spec_ema = 1.0
        slot.spec_miss = 0
        slot.epoch += 1
        if slot.session:
            # only drop the mapping if it still points HERE — clear_sessions
            # may have already remapped this session name to another slot
            if self.sessions.get(slot.session) == slot.idx:
                self.sessions.pop(slot.session, None)
                self._flush_parked_snapshot(slot.session)
            slot.session = ""

    def _ensure_device_state(self) -> None:
        """After a worker fault: the failed call may have CONSUMED its
        donated inputs (cache, decode carry) without producing outputs —
        every later dispatch would then raise 'array deleted' forever.
        Reallocate anything lost so the engine keeps serving (sessions
        restart cold; the store-side KV snapshots still allow resume)."""
        lost = False
        for arr in (self.cache.k, self.cache.v):
            try:
                if arr.is_deleted():
                    lost = True
            except Exception:
                lost = True
        if lost:
            self.cache = self._alloc_cache()
            self.cache_resets += 1
            for slot in self.slots:
                if slot.request is not None:
                    self._fail_item(slot.request, RuntimeError("KV arena reset"))
                self._reset_slot(slot)
            self.sessions.clear()
            if self.paged:
                # the pool's contents are gone: every session, prefix pin,
                # and quarantined id referenced the lost arrays
                with self._page_lock:
                    self.paged_sessions.clear()
                    self._prefix_entries.clear()
                    self._prefix_bytes = 0
                    self._page_free = list(range(self._data_pages - 1, -1, -1))
                    self._page_refs[:] = 0
                    self._page_quarantine = []
                    for i in range(self.max_batch):
                        self._bt[i, :] = self._scratch_page(i)
        carry_lost = False
        for arr in (
            self._dtok, self._dpos, self._dtemps, self._dtopk, self._dtopp,
            self._dhist, self._dhlen,
        ):
            try:
                if arr.is_deleted():
                    carry_lost = True
            except Exception:
                carry_lost = True
        if carry_lost:
            (
                self._dtok,
                self._dpos,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                self._dhist,
                self._dhlen,
            ) = self._alloc_carry()
            # fresh carry parks every lane at scratch: decoding requests
            # lost their device position and cannot continue
            for slot in self.slots:
                if slot.decoding and slot.request is not None:
                    self._fail_item(slot.request, RuntimeError("decode carry reset"))
                    self._reset_slot(slot)
                slot.decoding = False
        stage_lost = False
        for arr in (
            self._stok, self._spos, self._stemps, self._stopk, self._stopp,
            self._shist, self._shlen,
        ):
            try:
                if arr.is_deleted():
                    stage_lost = True
            except Exception:
                stage_lost = True
        if stage_lost:
            (
                self._stok,
                self._spos,
                self._stemps,
                self._stopk,
                self._stopp,
                self._shist,
                self._shlen,
            ) = self._alloc_carry()
            self._staged_lane = None

    def _do_restore(self, cmd: RestoreCmd) -> None:
        from .checkpoint import restore_kv_slot

        ok = False
        try:
            if self.paged:
                ok = self._do_restore_paged(cmd)
                return
            slot = self._find_slot(cmd.session)
            if slot is not None and cmd.position < self.max_seq - 1:
                self.cache = restore_kv_slot(self.cache, slot.idx, cmd.k, cmd.v)
                slot.position = cmd.position
                slot.pending_token = cmd.pending_token
                # a restored slot is LIVE now: without this, its last_used
                # is whatever its previous occupant left (often 0), so the
                # very next admission/restore picks it as the LRU victim
                # and silently evicts the session that was just restored
                # (the paged restore path already stamps last_used)
                slot.last_used = time.monotonic()
                ok = True
        finally:
            # resolve even on exception (shape-mismatched snapshots from a
            # redeployed model config must not hang the caller)
            cmd.loop.call_soon_threadsafe(_resolve_value, cmd.future, ok)

    def _do_restore_paged(self, cmd: RestoreCmd) -> bool:
        """Restore into PAGES, not a lane: the session enters residency
        without occupying a compute lane at all (a restored session that
        never speaks again costs only its pages). Exhaustion surfaces as
        False — the caller re-prefills instead."""
        if not cmd.session or cmd.position >= self.max_seq - 1 or cmd.position <= 0:
            return False
        # under _page_lock against API-thread clear_sessions: the
        # existing-session teardown and the new binding must be atomic
        with self._page_lock:
            existing = self.paged_sessions.get(cmd.session)
            if existing is not None:
                if existing.lane is not None:
                    return False  # mid-generation: never clobber live KV
                self._free_session_pages(existing)
                self.paged_sessions.pop(cmd.session, None)
                self.sessions.pop(cmd.session, None)
        count = (cmd.position - 1) // self.page_size + 1
        try:
            ids = self._alloc_pages(count, serving=False)
        except EngineOverloaded:
            return False
        k = np.asarray(cmd.k)
        v = np.asarray(cmd.v)
        pad = count * self.page_size - k.shape[1]
        if pad:
            widths = [(0, 0), (0, pad)] + [(0, 0)] * (k.ndim - 2)
            k = np.pad(k, widths)
            v = np.pad(v, widths)
        dtype = self.cache.k.dtype
        shape = (k.shape[0], count, self.page_size, *k.shape[2:])
        self.cache = self._restore_fn_paged(count)(
            self.cache,
            jnp.asarray(np.asarray(ids, dtype=np.int32)),
            jnp.asarray(k.reshape(shape), dtype),
            jnp.asarray(v.reshape(shape), dtype),
        )
        sess = PagedSession(
            name=cmd.session,
            pages=ids,
            position=cmd.position,
            pending_token=cmd.pending_token,
            last_used=time.monotonic(),
        )
        with self._page_lock:
            self.paged_sessions[cmd.session] = sess
            self.sessions[cmd.session] = -1
        return True

    def _fail_item(self, item, error: Exception) -> None:
        fut = getattr(item, "future", None)
        loop = getattr(item, "loop", None)
        if fut is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(_reject, fut, error)
            except RuntimeError:
                pass  # caller's loop already closed; nobody left to notify

    def _admit_prologue(
        self, position: int, pending_token: int | None, req: GenRequest
    ) -> tuple[list[int], int | None, bool]:
        """Shared admission prologue — ONE implementation for both arenas,
        because greedy A/B parity between them hinges on these semantics
        matching exactly. Splices the held-out pending token into the
        prompt, decides whether the continuation fits the budget (reset
        otherwise — and the pending token belongs to the context being
        DISCARDED: keeping it would prefill one stale token that an engine
        without a held-out pending never sees, breaking parity at exactly
        the reset boundary), and trims an over-long prompt to its tail.
        Returns (prompt, original_pending, reset)."""
        prompt = list(req.prompt_ids)
        pend = pending_token
        if pend is not None:
            prompt = [pend] + prompt
        budget = self.max_seq - 1 - req.max_tokens
        reset = position + len(prompt) > budget
        if reset and pend is not None:
            prompt = prompt[1:]
        if len(prompt) > budget:
            prompt = prompt[-budget:]  # keep the tail
        return prompt, pend, reset

    def _try_admit(self, req: GenRequest) -> bool:
        if self.paged:
            return self._try_admit_paged(req)
        slot = self._find_slot(req.session)
        if slot is None:
            return False
        prompt, _, reset = self._admit_prologue(slot.position, slot.pending_token, req)
        slot.pending_token = None
        if reset:
            # continuation didn't fit: reset the session's KV
            slot.position = 0
            slot.epoch += 1
        # Fresh context (position 0): fork the longest cached prefix into
        # this slot instead of re-prefilling it — a second session with a
        # shared system prompt skips ~all of its prefill. Continuing
        # sessions already hold their context in KV; nothing to fork.
        forked = 0
        fresh = slot.position == 0
        # drafting corpus mirrors the slot's fed token stream exactly: a
        # fresh context replaces it, a continuing turn appends (the pending
        # token rides in via the prompt, having been held out at finish)
        if fresh:
            slot.spec_hist = list(prompt)
        else:
            slot.spec_hist.extend(prompt)
            del slot.spec_hist[: -self.max_seq]
        if self._prefix_active and fresh:
            if self._prefix_levels and len(prompt) > self._prefix_levels[0]:
                hit = self._prefix_lookup(prompt)
                if hit is not None:
                    key, entry = hit
                    b = key[0]
                    try:
                        self.cache = self._prefix_fork_fn(b)(
                            self.cache, jnp.int32(slot.idx), entry.k, entry.v
                        )
                    except Exception:
                        # the fork may have consumed its donated cache
                        # without producing one — repair device state, then
                        # let _admit_waiting fail this request
                        self._ensure_device_state()
                        raise
                    forked = b
                    slot.position = b
                    entry.hits += 1
                    entry.last_used = time.monotonic()
                    self._prefix_entries.move_to_end(key)
                    self.prefix_hits += 1
                    self.prefix_tokens_saved += b
                    # the fork streams the entry's KV once (copy, no FLOPs
                    # — that's the point); keeps the MBU model honest
                    self.hbm_bytes_read += b * self._kv_bytes_per_pos
                else:
                    self.prefix_misses += 1
            # track the fresh context so the final prefill chunk registers
            # its bucket-prefixes (including levels above a partial hit)
            slot.prefix_ctx = list(prompt)
        else:
            slot.prefix_ctx = None
        # admit: the slot is busy from here; the worker's prefill tick feeds
        # the prompt through chunk-by-chunk, interleaved with decode steps
        slot.request = req
        slot.pending_prompt = prompt[forked:]
        slot.last_used = time.monotonic()
        return True

    def _try_admit_paged(self, req: GenRequest) -> bool:
        """Paged admission: bind the session (resident or new) to ANY free
        compute lane — lanes carry no KV affinity, the pages do — and map
        the longest cached prefix as refcounted pages instead of forking a
        copy. Mirrors the dense _try_admit flow step for step so greedy
        scheduling (and therefore token streams) stay identical. Runs
        under _page_lock: an API-thread clear_sessions checks ``lane is
        None`` and frees pages, so it must never interleave with a bind —
        a session cleared between the lookup and ``sess.lane = idx`` would
        have its just-mapped pages returned to the pool and handed to
        another session while this lane writes through them."""
        # Pre-drain the quarantine OUTSIDE the lock when the pool looks
        # short for this request: _alloc_pages' quarantine reap waits on
        # in-flight device work (jax.block_until_ready), and paying that
        # wait while holding _page_lock would stall every API-thread lock
        # consumer (stats/clear_sessions) for the duration. Out here only
        # the worker waits; inside, the reap then finds the quarantine
        # already empty. (Worst-case page need for this admission; a race
        # refilling the quarantine in between just falls back to the
        # locked wait, which is correct, merely slower.)
        need = (len(req.prompt_ids) + req.max_tokens) // self.page_size + 2
        self._reap_quarantine_if_short(min(need, self._n_blocks))
        with self._page_lock:
            return self._try_admit_paged_locked(req)

    def _try_admit_paged_locked(self, req: GenRequest) -> bool:
        name = req.session
        sess = self.paged_sessions.get(name) if name else None
        if sess is not None and sess.lane is not None:
            return False  # session busy: one request per session at a time
        lane = next((s for s in self.slots if s.request is None), None)
        if lane is None:
            return False
        fresh_session = sess is None
        if fresh_session:
            sess = PagedSession(name=name)
        prompt, pend, reset = self._admit_prologue(sess.position, sess.pending_token, req)
        sess.pending_token = None
        if reset:
            # continuation didn't fit: reset the session's KV (pages too)
            self._free_session_pages(sess)
            sess.position = 0
        # pre-request state for pool-exhaustion rollback: the pending token
        # was just consumed into the prompt and must return with a rollback,
        # the position is about to advance (prefix map below; spec accepts
        # mid-request), and spec_hist is about to be extended in place.
        # After a context reset there is no pre-request state worth keeping
        # (admit_position 0 → drop).
        sess.admit_pending = pend if sess.position > 0 else None
        sess.admit_position = sess.position
        sess.admit_spec_hist = list(sess.spec_hist) if sess.position > 0 else []
        forked = 0
        fresh = sess.position == 0
        if fresh:
            sess.spec_hist = list(prompt)
        else:
            sess.spec_hist.extend(prompt)
            del sess.spec_hist[: -self.max_seq]
        try:
            if self._prefix_active and fresh:
                if self._prefix_levels and len(prompt) > self._prefix_levels[0]:
                    hit = self._prefix_lookup(prompt)
                    if hit is not None and hit[1].pages is not None:
                        key, entry = hit
                        forked = self._map_prefix_pages(sess, key, entry)
                    else:
                        self.prefix_misses += 1
                lane.prefix_ctx = list(prompt)
            else:
                lane.prefix_ctx = None
        except Exception:
            # partial mappings must not leak a half-built session into
            # residency: free what was mapped, then surface the error
            # (_admit_waiting fails the request — 429 for pool exhaustion)
            self._free_session_pages(sess)
            if not fresh_session and name:
                self.paged_sessions.pop(name, None)
                self.sessions.pop(name, None)
            raise
        # bind: the lane mirrors the session while the request is in flight
        if fresh_session and name:
            self.paged_sessions[name] = sess
        sess.lane = lane.idx
        sess.last_used = time.monotonic()
        if name:
            self.sessions[name] = lane.idx
        lane.psess = sess
        lane.session = name
        lane.position = sess.position
        lane.pending_token = None
        lane.spec_hist = sess.spec_hist
        lane.spec_ema = sess.spec_ema
        lane.spec_miss = sess.spec_miss
        lane.epoch += 1
        lane.request = req
        lane.pending_prompt = prompt[forked:]
        lane.last_used = time.monotonic()
        self._bind_lane_bt(lane, sess)
        return True

    def _map_prefix_pages(self, sess: PagedSession, key: tuple, entry) -> int:
        """Zero-copy prefix fork: the session's block table maps the
        entry's full pages read-only (one refcount bump per page, no
        device traffic); only a partial tail page is copied — and only
        when the level isn't page-aligned. Returns the forked token count."""
        b = key[0]
        # take this session's page references FIRST: the tail-copy
        # allocation below may reclaim, and reclaim may evict THIS entry
        # (it is not re-LRU'd until the hit is recorded) — with the refs
        # already held, an eviction only drops the arena's pin while the
        # pages (and the tail-copy source) stay live for the mapping
        pages = list(entry.pages)
        tail_src = entry.tail_page
        with self._page_lock:
            for pid in pages:
                self._page_refs[pid] += 1
            if tail_src is not None:
                self._page_refs[tail_src] += 1
        tail_copy = None
        try:
            if tail_src is not None:
                # copy-on-write at the partial last page: this session will
                # write positions [b, page boundary) into that same page
                tail_copy = self._alloc_pages(1, serving=True)[0]
                self.cache = self._page_copy_fn()(
                    self.cache, jnp.int32(tail_src), jnp.int32(tail_copy)
                )
        except BaseException:
            with self._page_lock:
                for pid in pages:
                    self._decref_page(pid)
                if tail_copy is not None:
                    self._decref_page(tail_copy)
            raise
        finally:
            if tail_src is not None:
                self._decref_page(tail_src)
        sess.pages = pages
        sess.shared = len(pages)
        if tail_copy is not None:
            sess.pages.append(tail_copy)
        sess.position = b
        entry.hits += 1
        entry.last_used = time.monotonic()
        if key in self._prefix_entries:  # the alloc may have evicted it
            self._prefix_entries.move_to_end(key)
        self.prefix_hits += 1
        self.prefix_tokens_saved += b
        self.prefix_pages_shared += len(pages)
        # HBM traffic: ONLY the tail copy streams bytes — the whole point
        # of page mapping vs the dense fork's full-prefix copy
        if tail_copy is not None:
            self.hbm_bytes_read += self.page_size * self._kv_bytes_per_pos
        return b

    def _find_slot(self, session: str) -> Slot | None:
        if session and session in self.sessions:
            slot = self.slots[self.sessions[session]]
            if slot.request is None:
                return slot
            return None  # session busy: one request per session at a time
        # fresh slot: prefer never-used, else LRU idle session
        idle = [s for s in self.slots if s.request is None]
        if not idle:
            return None
        fresh = [s for s in idle if not s.session]
        slot = fresh[0] if fresh else min(idle, key=lambda s: s.last_used)
        if slot.session and self.sessions.get(slot.session) == slot.idx:
            self.sessions.pop(slot.session, None)  # evict LRU session's KV
            self._count_eviction("session", time.monotonic() - slot.last_used)
            self._flush_parked_snapshot(slot.session)
        slot.session = session
        slot.position = 0
        slot.pending_token = None  # stale state from the previous occupant
        slot.pending_prompt = []
        slot.spec_hist = []
        slot.spec_ema = 1.0  # new occupant: optimistic until measured
        slot.spec_miss = 0
        slot.epoch += 1
        if session:
            self.sessions[session] = slot.idx
        return slot

    def _bucket(self, n: int) -> int:
        for b in PREFILL_BUCKETS:
            if n <= b:
                return b
        return PREFILL_BUCKETS[-1]

    def _prefill_tick(self) -> None:
        """Feed ONE chunk of one pending prompt through the model (FIFO by
        submission time). Non-final chunks only populate the slot's KV; the
        final chunk samples the first token. Interleaving these ticks with
        decode steps bounds how long one long prompt can stall every active
        generation: one chunk's latency, not the whole prompt's."""
        slots = [s for s in self.slots if s.request is not None and s.pending_prompt]
        if not slots:
            return
        # admission-first: a prompt that has not started prefilling yet beats
        # an in-progress prompt's next chunk, so one long prompt cannot
        # monopolize the tick and push new arrivals' admission latency to
        # its full prefill time; ties (and steady state) stay FIFO
        slot = min(
            slots,
            key=lambda s: (s.request.prefill_started_at is not None, s.request.submitted_at),
        )
        self._prefilling_slot = slot  # fault attribution (worker loop)
        req = slot.request
        # failpoint: a poisoned prefill fails THIS request only — the worker
        # loop's per-request isolation (VERDICT r4 item 1b) is what the
        # chaos soak exercises through this seam. Warmup's synthetic
        # requests (empty id) are exempt: fault injection targets serving
        # traffic, and an env-armed failpoint must not brick engine boot.
        if req.id:
            faults.fire("engine.prefill")
        if req.prefill_started_at is None:
            req.prefill_started_at = time.monotonic()
            self.admission_ms_recent.append(
                1000 * (req.prefill_started_at - req.submitted_at)
            )
            # promote-overlap accounting: the interval from the tier
            # promotion's start to this first prefill dispatch is restore
            # latency HIDDEN behind the queue-wait phase of TTFT
            t0 = (
                self._tier_promote_started.pop(req.session, None)
                if req.session
                else None
            )
            if t0 is not None:
                hidden = 1000 * (req.prefill_started_at - t0)
                self.tier_promote_overlap_ms_total += hidden
                self.tier_promote_overlap_ms_recent.append(hidden)
        chunk = slot.pending_prompt[: self.prefill_chunk]
        slot.pending_prompt = slot.pending_prompt[self.prefill_chunk :]
        final = not slot.pending_prompt
        n = len(chunk)
        bucket = self._bucket(n)
        padded = chunk + [0] * (bucket - n)
        # padding positions continue past the real tokens; every such slot is
        # rewritten by a later real token (next chunk or decode) before any
        # query can attend to it, and the position mask hides the rest
        positions = np.arange(slot.position, slot.position + bucket, dtype=np.int32)
        tokens = jnp.asarray(np.array(padded, dtype=np.int32)[None])
        pos = jnp.asarray(positions[None])
        if self.paged:
            # pages cover the REAL tokens only; bucket-padding writes past
            # them fall into the lane's scratch page via the table default
            # (and clamp in-kernel past the logical arena) — exactly as
            # invisible as the dense path's dropped out-of-range scatter
            try:
                self._ensure_lane_pages(
                    slot, slot.position + n - 1, serving=bool(req.id)
                )
            except EngineOverloaded as e:
                # policy backpressure, not a fault: fail THIS request with
                # the typed 429 and roll the session back — the worker
                # loop's generic prefill handler would count a worker
                # error and destroy the resident session
                self._fail_item(req, e)
                self._abandon_slot(slot, rollback=True)
                return
            last_logits, self.cache = self._prefill(
                self.params,
                self.cache,
                jnp.asarray(self._bt[slot.idx : slot.idx + 1]),
                tokens,
                pos,
                jnp.int32(n),
            )
        else:
            last_logits, self.cache = self._prefill(
                self.params, self.cache, jnp.int32(slot.idx), tokens, pos, jnp.int32(n)
            )
        # n real tokens, each attending ~its own position of context
        self.flops_done += n * self.cfg.flops_per_token(slot.position + n // 2)
        self.hbm_bytes_read += self.param_hbm_bytes + (
            (slot.position + n // 2) * self._kv_bytes_per_pos
        )
        slot.position += n
        slot.last_used = time.monotonic()
        if not final:
            return
        # whole fresh context now in KV: register its bucket-prefixes in
        # the arena (async device copies; positions [0:b] are real tokens —
        # the final chunk's padding lands strictly above slot.position)
        self._prefix_register(slot)
        self._rng, key = jax.random.split(self._rng)
        first = sample_step(
            last_logits[None],
            key,
            jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_k], jnp.int32),
            jnp.asarray([req.top_p], jnp.float32),
            greedy_cond=self.mesh is None,
            approx_topk=self.approx_topk,
        )
        hist_row = None
        hist_n = 0
        if self.inloop_spec:
            # seed the in-loop drafter with the prompt tail, right-aligned;
            # the inject scatter shifts it left one slot so the sampled
            # first token occupies the newest position
            ctx = req.prompt_ids[-(FUSED_HIST_W - 1):]
            row = np.zeros((FUSED_HIST_W,), np.int32)
            if ctx:
                row[FUSED_HIST_W - len(ctx):] = ctx
            hist_row = jnp.asarray(row)
            hist_n = min(len(ctx) + 1, FUSED_HIST_W)
        # point the slot's decode lane at this prompt's continuation WITHOUT
        # waiting for the sampled token to reach the host — decode chunks
        # chain from it on device; the value lands via the readback queue.
        # If a fused loop is already in flight and the staging slot is free,
        # write the SHADOW carry instead: the pipelined next loop absorbs
        # the lane at its entry (double-buffered injection) rather than
        # waiting out an exit-and-redispatch.
        use_stage = (
            self.fused_decode
            and self._fused_inject
            and self._staged_lane is None
            # host-side speculation reads the LIVE carry for verify rounds;
            # a staged lane is invisible there until absorbed, so staging is
            # only safe when spec runs in-loop (or not at all)
            and (self.inloop_spec or not self._spec_active)
            and any(e[0] == "fused" for e in self._readbacks)
        )
        if use_stage:
            self._stage_lane(
                slot.idx,
                first[0].astype(jnp.int32),
                slot.position,
                req.temperature,
                req.top_k,
                req.top_p,
                hist_row,
                hist_n,
            )
            self._staged_lane = slot.idx
        else:
            if (
                self.fused_decode
                and self._fused_inject
                and any(e[0] == "fused" for e in self._readbacks)
            ):
                # staging slot occupied with a loop in flight: fall back to
                # the direct-injection path (exit-and-redispatch semantics)
                self.fused_inject_fallbacks_total += 1
            self._inject_lane(
                slot.idx,
                first[0].astype(jnp.int32),
                slot.position,
                req.temperature,
                req.top_k,
                req.top_p,
                hist_row,
                hist_n,
            )
        slot.dev_position = slot.position
        slot.decoding = True
        req.prefill_done_at = time.monotonic()
        req.dispatched = 1  # the prefill-sampled first token
        self.prefills += 1
        try:
            first.copy_to_host_async()
        except Exception:
            pass
        self._readbacks.append(("first", slot, req, first, time.monotonic()))

    def _finish(self, slot: Slot, pending_last: bool) -> None:
        """``pending_last``: the final generated token was sampled but not yet
        fed through the model (it is absent from the slot's KV); carry it
        into the session's next prompt. When a chunked decode already fed it
        (mid-chunk finish), the caller passes False."""
        req = slot.request
        slot.request = None
        slot.last_used = time.monotonic()
        slot.pending_token = (req.generated[-1] if req.generated else None) if pending_last else None
        # fold the reply into the drafting corpus; a held-out pending token
        # re-arrives via the next turn's prompt, so it is excluded here
        slot.spec_hist.extend(
            req.generated[:-1] if slot.pending_token is not None else req.generated
        )
        del slot.spec_hist[: -self.max_seq]
        if slot.decoding:
            # park the lane: in-flight chunks keep decoding it (their tokens
            # are skipped at processing — request identity mismatch) until
            # this injection lands in dispatch order
            slot.decoding = False
            slot.dev_position = self.scratch_pos
            self._park_lane(slot.idx)
        breakdown = None
        if req.ttft_ms and req.prefill_started_at and req.prefill_done_at:
            breakdown = {
                "queue_ms": round(1000 * (req.prefill_started_at - req.submitted_at), 2),
                "prefill_ms": round(
                    1000 * (req.prefill_done_at - req.prefill_started_at), 2
                ),
                "first_readback_ms": round(
                    req.ttft_ms - 1000 * (req.prefill_done_at - req.submitted_at), 2
                ),
            }
        result = {
            "text": self.tokenizer.decode(req.generated),
            "tokens": req.generated,
            "prompt_tokens": len(req.prompt_ids),
            "completion_tokens": len(req.generated),
            "ttft_ms": round(req.ttft_ms, 2) if req.ttft_ms else None,
            # per-request TTFT phase decomposition: queue-wait / prefill /
            # first-readback (sums to ttft_ms up to rounding)
            "ttft_breakdown": breakdown,
        }
        # Paged: settle the SESSION before resolving the caller — sync the
        # lane's final state back, stage any parked snapshot (the staging
        # reads the synced session), then release the compute lane (the
        # session stays resident in pages, holding zero lanes between
        # turns; overshoot page tails go back to the pool). Resolving last
        # means "await chat() returned" implies the session is settled —
        # callers and tests can inspect residency without racing the worker.
        if self.paged:
            if slot.psess is not None:
                slot.psess.position = slot.position
                slot.psess.pending_token = slot.pending_token
            self._service_parked_snapshot(slot)
            self._detach_lane(slot)
        req.loop.call_soon_threadsafe(_resolve, req.future, result)
        # a cancel that raced a natural finish loses: drop its stale marker
        with self._lock:
            self._cancel_requested.pop(req.id, None)
        if not self.paged:
            # settle point: the slot is idle RIGHT NOW — stage any snapshot
            # that parked while this request was generating
            self._service_parked_snapshot(slot)

    def _decode_dispatch(self) -> None:
        """Dispatch one decode chunk chained on the device carry and queue
        its token readback; processing happens a pipeline slot later. Chunk
        size is policy (_pick_chunk): full at steady state, the smallest
        compiled bucket while anyone waits for admission/prefill."""
        snapshot = [
            (s, s.request, s.dev_position)
            for s in self.slots
            if s.decoding and s.request is not None
        ]
        if not snapshot:
            return
        needed = max(r.max_tokens - r.dispatched for _, r, _ in snapshot)
        if needed <= 0:
            # every live lane's whole budget is already in flight: another
            # chunk would be pure garbage steps while the readbacks land
            return
        # failpoint: a decode fault is batch-wide by construction (one
        # compiled call covers every lane) — the worker fails the in-flight
        # batch and reallocates device state, then keeps serving. Warmup's
        # synthetic requests (empty id) are exempt, same as the prefill seam.
        if any(r.id for _, r, _ in snapshot):
            faults.fire("engine.decode_step")
        chunk = self._pick_chunk(needed)
        if self.paged:
            # pre-allocate pages covering every step of the chunk so the
            # block table is constant across the compiled scan; a lane the
            # pool can't cover fails with 429 backpressure — the others
            # keep decoding
            kept = []
            for s, r, p in snapshot:
                try:
                    self._ensure_lane_pages(
                        s, min(p + chunk - 1, self.max_seq - 2), serving=bool(r.id)
                    )
                    kept.append((s, r, p))
                except EngineOverloaded as e:
                    self._fail_item(r, e)
                    self._abandon_slot(s, rollback=True)
            snapshot = kept
            if not snapshot:
                return
        self._rng, key = jax.random.split(self._rng)
        keys = jax.random.split(key, chunk)
        toks, self._dtok, self._dpos, self.cache = self._decode_n(
            self.params,
            self.cache,
            *self._bt_arg(),
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            keys,
        )
        for s, r, _ in snapshot:
            s.dev_position += chunk
            r.dispatched += chunk
        self.decode_chunk_hist[chunk] = self.decode_chunk_hist.get(chunk, 0) + 1
        self.decode_steps += 1
        self._occupancy_sum += len(snapshot) / self.max_batch
        # weights stream once per scan step; each live lane streams its KV
        # prefix (parked lanes re-read the scratch row — not useful traffic)
        self.hbm_bytes_read += chunk * self.param_hbm_bytes + sum(
            chunk * (p + chunk // 2) * self._kv_bytes_per_pos for _, _, p in snapshot
        )
        try:
            toks.copy_to_host_async()
        except Exception:
            pass
        self._readbacks.append(("chunk", snapshot, toks, time.monotonic()))

    def _fused_dispatch(self) -> None:  # atp: hot
        """Dispatch one fused on-device decode loop (fused_decode=True's
        replacement for _decode_dispatch): same snapshot/paged
        pre-allocation discipline, but the compiled call is the dynamic-
        rung while_loop (_fused_fn) that masks finished lanes, runs the
        in-loop drafter/verifier, absorbs the staged injection lane, and
        early-exits on device — the readback queued here is the loop's
        single packed (tokens, lengths, reasons, steps, spec counters)
        transfer. The loop bound ``nsteps`` is a runtime operand of ONE
        compiled executable (_pick_fused_chunk), so the admission
        contention story carries over — contention shrinks the loop,
        newcomers' prefill still preempts at rung boundaries — without a
        per-rung executable ladder. Host-side speculation composes between
        fused loops when in-loop spec is off; with it on, drafting happens
        inside the loop body and _try_speculate is bypassed."""
        base = [
            (s, s.request, s.dev_position)
            for s in self.slots
            if s.decoding and s.request is not None
        ]
        if not base:
            return
        needed = max(r.max_tokens - r.dispatched for _, r, _ in base)
        if needed <= 0:
            return
        # failpoint: same batch-wide seam as engine.decode_step, but its
        # own catalog name — chaos schedules can cut (or delay, for the
        # SIGKILL-mid-loop soak phase) exactly the fused path
        if any(r.id for _, r, _ in base):
            faults.fire("engine.fused_decode")
        chunk = self._pick_fused_chunk()
        if self.paged:
            kept = []
            for s, r, p in base:
                try:
                    # +FUSED_SPEC_K: the in-loop verifier forwards up to K
                    # draft positions past the last real token; those writes
                    # must land in owned pages even when rejected
                    self._ensure_lane_pages(
                        s,
                        min(p + chunk + FUSED_SPEC_K, self.max_seq - 2),
                        serving=bool(r.id),
                    )
                    kept.append((s, r, p))
                except EngineOverloaded as e:
                    self._fail_item(r, e)
                    self._abandon_slot(s, rollback=True)
            base = kept
            if not base:
                return
        self._rng, key = jax.random.split(self._rng)
        keys = jax.random.split(key, self._fused_cap)
        live = np.zeros((self.max_batch,), dtype=bool)
        budgets = np.zeros((self.max_batch,), dtype=np.int32)
        ign = np.zeros((self.max_batch,), dtype=bool)
        armed = np.zeros((self.max_batch,), dtype=bool)
        for s, r, _ in base:
            live[s.idx] = True
            # chunk+1 emission cap: the most one loop can emit (spec can
            # beat one-per-iteration). The device NEVER finishes on budget
            # — cap-hit lanes freeze and the host rescan decides, so this
            # estimate being ≥ true remaining (dispatched counts
            # iterations, not emissions) is the safe direction
            budgets[s.idx] = min(r.max_tokens - r.dispatched, chunk + 1)
            ign[s.idx] = bool(r.ignore_eos)
        if self._staged_lane is not None:
            armed[self._staged_lane] = True
        # per-lane upper bound on this loop's device-position advance —
        # used for dev_position bookkeeping (paging must only ever
        # over-ensure, never under)
        snapshot = [(s, r, p, int(budgets[s.idx])) for s, r, p in base]
        (
            packed,
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            self._dhist,
            self._dhlen,
            self.cache,
        ) = self._fused_fn()(
            self.params,
            self.cache,
            *self._bt_arg(),
            self._dtok,
            self._dpos,
            self._dtemps,
            self._dtopk,
            self._dtopp,
            self._dhist,
            self._dhlen,
            self._stok,
            self._spos,
            self._stemps,
            self._stopk,
            self._stopp,
            self._shist,
            self._shlen,
            jnp.asarray(armed),
            jnp.asarray(live),
            jnp.asarray(budgets),
            jnp.asarray(ign),
            keys,
            jnp.int32(chunk),
        )
        if self._staged_lane is not None:
            # the loop just dispatched absorbs the staged lane at entry
            self._staged_lane = None
            self.fused_injections_total += 1
        for s, r, _, adv in snapshot:
            # upper bound for unfinished lanes; finished lanes park at
            # scratch on device and their host state is settled (and
            # dev_position corrected) at processing (_process_fused)
            s.dev_position += adv
            r.dispatched += chunk
        self.fused_loops_total += 1
        self.decode_chunk_hist[chunk] = self.decode_chunk_hist.get(chunk, 0) + 1
        self.decode_steps += 1
        self._occupancy_sum += len(snapshot) / self.max_batch
        try:
            packed.copy_to_host_async()
        except Exception:
            pass
        self._readbacks.append(("fused", snapshot, packed, chunk, time.monotonic()))

    def _pick_fused_chunk(self) -> int:  # atp: hot
        """Loop-bound policy for the fused dispatcher. ``nsteps`` is a
        runtime operand (no per-rung executables), so the only tradeoff is
        responsiveness: a longer loop amortizes dispatch/readback overhead
        per token, a shorter one returns to admission/prefill work sooner.
        Steady state rides the static cap (FUSED_RUNG_MULT × decode_chunk);
        contention — a mid-prefill prompt or an admissible waiter — drops
        to the smallest ladder rung, exactly like _pick_chunk. Budget tails
        need no shrinking: per-lane caps freeze finished lanes and the
        whole-batch early exit ends the loop the iteration everyone is
        inactive."""
        if not self.adaptive_decode:
            return self.decode_chunk
        contended = any(s.request is not None and s.pending_prompt for s in self.slots)
        if not contended and (self._waiting or not self._queue.empty()):
            contended = any(s.request is None for s in self.slots)
        if contended and self._decode_ladder[0] < self._fused_cap:
            self.decode_chunks_shrunk += 1
            return self._decode_ladder[0]
        return self._fused_cap

    def _pick_chunk(self, needed: int, tail_shrink: bool = True) -> int:
        """Adaptive decode-chunk policy (the admission-aware half of the
        scheduler). Contention — a queued/waiting request or a mid-prefill
        prompt — shrinks to the smallest compiled bucket, so the worker gets
        back to admission/prefill work after ~one ITL instead of a full
        chunk wall (the wall WAS the ~180 ms admission half of single-chip
        TTFT). Otherwise: the smallest bucket covering the remaining token
        budget, so sequence tails don't dispatch overshoot garbage. Steady
        state with budget to burn returns the full chunk — ITL and HBM
        efficiency are untouched when nobody is waiting.

        ``tail_shrink=False`` is the fused dispatcher's mode: its in-loop
        budget masks park finishing lanes on device and the whole-batch
        early exit ends the loop the step everyone is done, so a budget
        tail costs nothing extra on the top rung — and riding the top rung
        pays ONE readback where the shrinking ladder pays one per rung.
        The contention downshift still applies (a loop over live lanes
        can't early-exit on a waiter's behalf)."""
        if not self.adaptive_decode:
            return self.decode_chunk
        contended = any(s.request is not None and s.pending_prompt for s in self.slots)
        if not contended and (self._waiting or not self._queue.empty()):
            # a queued waiter only benefits from a shrunk chunk if it can
            # actually be admitted (a free slot): when every slot is mid-
            # generation the waiter is gated on a FINISH, not on the worker
            # loop's cadence — keep the full chunk or a saturated engine's
            # throughput would collapse to chunk-1 dispatch overhead
            contended = any(s.request is None for s in self.slots)
        if contended and self._decode_ladder[0] < self.decode_chunk:
            self.decode_chunks_shrunk += 1
            return self._decode_ladder[0]
        if not tail_shrink:
            return self.decode_chunk
        target = max(1, min(needed, self.decode_chunk))
        for c in self._decode_ladder:
            if c >= target:
                return c
        return self.decode_chunk

    # -- self-speculative decoding (worker thread) ------------------------
    #
    # Prompt-lookup drafting: agentic traffic (tool-call JSON, flattened
    # histories, retrieval-grounded answers) constantly re-emits spans that
    # already exist in the context, so the slot's OWN token stream is the
    # draft model — zero extra weights. Per round, a host-side drafter
    # proposes up to gamma continuation tokens per lane; one compiled
    # verify forward (t = k+1, the prefill path at per-lane positions)
    # scores every lane's drafts in parallel; the longest agreeing prefix
    # is accepted and the slot's KV position is rewound past rejected
    # tokens (their cache writes sit beyond the live length, where the
    # position mask hides them until the stream overwrites them — the same
    # invariant chunked-decode overshoot already relies on). Greedy lanes
    # are bit-exact with plain decode (acceptance = argmax agreement, the
    # correction token IS the argmax the plain path would have sampled);
    # temperature lanes use standard speculative rejection sampling with a
    # point-mass proposal, which leaves the output distribution unchanged.

    def _verify_fn(self, K: int):
        """Compiled k-token verify step for draft bucket ``K``: feed each
        lane [carry_token, draft_0..draft_{K-1}] at positions [p..p+K],
        accept the longest agreeing draft prefix, and emit accepted drafts
        plus the model's own token at the first unverified row. Returns
        (emitted [B,K+1], count [B], new_tok [B], new_pos [B], cache)."""
        fn = self._verify_fns.get(K)
        if fn is None:
            run_forward = self._run_forward

            def verify_body(
                params, cache, tok, pos, temps, topk, topp, drafts, dlen, key, bt=None
            ):
                # the paged pool's page axis says nothing about the logical
                # arena length — scratch comes from the engine statics there
                scratch = cache.k.shape[2] - 1 if bt is None else self.max_seq - 1
                toks = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B,K+1]
                offs = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
                # parked lanes (and padding rows past a lane's draft_len)
                # clamp at the scratch position, exactly like plain decode
                positions = jnp.minimum(pos[:, None] + offs, scratch)
                logits, cache = run_forward(params, toks, positions, cache, bt)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                k_acc, k_bonus = jax.random.split(key)
                # draft_j (= toks[:, j+1]) is scored by logits row j. Greedy
                # lanes accept on exact argmax agreement; sampled lanes
                # accept with prob p_j(draft_j) — rejection sampling with a
                # point-mass proposal keeps the output distribution intact.
                u = jax.random.uniform(k_acc, drafts.shape)
                probs = jax.nn.softmax(
                    logits[:, :K, :].astype(jnp.float32)
                    / jnp.maximum(temps, 1e-6)[:, None, None],
                    axis=-1,
                )
                p_draft = jnp.take_along_axis(
                    probs, drafts[:, :, None], axis=2
                )[:, :, 0]
                ok = jnp.where(
                    temps[:, None] <= 0.0, drafts == greedy[:, :K], u < p_draft
                )
                ok = ok & (jnp.arange(K, dtype=jnp.int32)[None, :] < dlen[:, None])
                a = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)  # [B]
                # correction/bonus from the first unverified row: on a
                # rejection the rejected draft is masked out of the residual
                # (max(p - q, 0) for a point-mass q is p minus that token);
                # when every draft accepted, row a is the bonus distribution
                row_a = jnp.take_along_axis(logits, a[:, None, None], axis=1)[:, 0]
                draft_a = jnp.take_along_axis(
                    toks, jnp.minimum(a + 1, K)[:, None], axis=1
                )[:, 0]
                rejected = a < dlen
                vocab = jnp.arange(row_a.shape[-1], dtype=jnp.int32)[None, :]
                row_a = jnp.where(
                    (vocab == draft_a[:, None]) & rejected[:, None], NEG_INF, row_a
                )
                # the bonus/correction token goes through the same per-lane
                # filtered sampler as plain decode (lanes with active
                # filters never draft — _spec_gamma gates them to 0 — so
                # the rejection-sampling acceptance above stays valid
                # against the unfiltered target)
                bonus = sample_step(
                    row_a, k_bonus, temps, topk, topp,
                    greedy_cond=self.mesh is None,
                    approx_topk=self.approx_topk,
                ).astype(jnp.int32)
                m = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
                shifted = jnp.concatenate(
                    [toks[:, 1:], jnp.zeros_like(tok)[:, None]], axis=1
                )
                emitted = jnp.where(m < a[:, None], shifted, 0) + jnp.where(
                    m == a[:, None], bonus[:, None], 0
                )
                count = a + 1
                new_pos = jnp.minimum(pos + count, scratch)
                return emitted, count, bonus, new_pos, cache

            if self.paged:

                def verify_paged(
                    params, cache, bt, tok, pos, temps, topk, topp, drafts, dlen, key
                ):
                    return verify_body(
                        params, cache, tok, pos, temps, topk, topp, drafts, dlen, key, bt
                    )

                fn = self._verify_fns[K] = jax.jit(
                    verify_paged, donate_argnums=(1, 3, 4)
                )
            else:

                def verify(
                    params, cache, tok, pos, temps, topk, topp, drafts, dlen, key
                ):
                    return verify_body(
                        params, cache, tok, pos, temps, topk, topp, drafts, dlen, key
                    )

                fn = self._verify_fns[K] = jax.jit(verify, donate_argnums=(1, 2, 3))
        return fn

    def _spec_gamma(self, slot: Slot) -> int:
        """Draft-length policy for one lane: EMA-scaled up to gamma_max,
        capped by the request's remaining token budget and the arena
        headroom (drafted positions must stay below scratch). Collapsed
        (low-EMA) and lookup-missing lanes return 0 except for a short
        probe draft every SPEC_PROBE_EVERY decode steps, so a workload
        shift re-opens speculation without taxing the steady state."""
        req = slot.request
        if req is None or not req.generated:
            return 0
        if req.temperature > 0.0 and (req.top_k > 0 or req.top_p < 1.0):
            # point-mass rejection sampling verifies against the UNFILTERED
            # target distribution; a filtered temperature lane would accept
            # drafts the filtered sampler could never emit. Such lanes ride
            # verify rounds draft-free (dlen=0 — the bonus token still goes
            # through their filters). Greedy lanes draft regardless: argmax
            # is invariant under top-k/top-p masking.
            return 0
        cap = min(
            self.spec_gamma_max,
            req.max_tokens - len(req.generated) - 1,
            self.max_seq - 2 - slot.position,
        )
        if cap <= 0:
            return 0
        if slot.spec_ema < SPEC_EMA_FLOOR or slot.spec_miss >= SPEC_MISS_BACKOFF:
            probe_due = self.decode_steps - slot.spec_probe_at >= SPEC_PROBE_EVERY
            return min(2, cap) if probe_due else 0
        return min(max(1, int(round(slot.spec_ema * self.spec_gamma_max))), cap)

    def _spec_draft(self, slot: Slot, gamma: int) -> list[int]:
        """Prompt-lookup draft: the tokens that followed the most recent
        earlier occurrence of the stream's trailing n-gram (longest of
        3-gram / 2-gram). The lookup iterates on the extended stream when
        a match runs out of continuation before ``gamma`` tokens — a
        looping stream (tool-call JSON, repeated structure) drafts the
        whole bucket, not just one cycle's tail. Reverse scans over the
        slot's fed stream — bounded by max_seq, microseconds next to a
        model forward."""
        seq = slot.spec_hist + slot.request.generated
        base = len(seq)
        while len(seq) - base < gamma:
            got = self._spec_lookup(seq, gamma - (len(seq) - base))
            if not got:
                break
            seq.extend(got)
        return [int(t) for t in seq[base:]]

    @staticmethod
    def _spec_lookup(seq: list, want: int) -> list:
        L = len(seq)
        for n in (3, 2):
            if L < n + 1:
                continue
            pat = seq[L - n :]
            floor = max(0, L - n - 1 - SPEC_LOOKUP_WINDOW)
            for i in range(L - n - 1, floor - 1, -1):
                if seq[i : i + n] == pat:
                    return seq[i + n : i + n + want]
        return []

    def _try_speculate(self) -> bool:
        """Run one speculative verify round if the batch has draftable
        lanes. Returns True when a round was dispatched-and-processed (the
        caller skips the plain decode dispatch for this iteration).

        Speculation is a STEADY-STATE optimization: under admission/prefill
        contention the plain ladder (which shrinks) keeps newcomers fast —
        a synchronous verify round would block exactly the queue polling
        that admits them — so contended iterations fall through to the
        plain path unconditionally."""
        if not self._spec_active:
            return False
        if self._waiting or not self._queue.empty():
            return False
        if any(s.request is not None and s.pending_prompt for s in self.slots):
            return False
        if not any(
            s.decoding and s.request is not None and self._spec_gamma(s) > 0
            for s in self.slots
        ):
            return False
        # drafting needs the host's view of every lane's stream to be
        # current: drain the readback pipeline (the drain keeps admitting —
        # _wait_admitting — so this costs sync, not admission latency)
        while self._readbacks:
            self._drain_readbacks(block=True)
            if self._sentinel:
                return True  # unwind; the worker loop re-checks the sentinel
        # the drain may have admitted new work: re-check contention
        if self._waiting or any(
            s.request is not None and s.pending_prompt for s in self.slots
        ):
            return False
        plan = []
        any_draft = False
        for s in self.slots:
            if not s.decoding or s.request is None:
                continue
            g = self._spec_gamma(s)
            d = self._spec_draft(s, g) if g > 0 else []
            if g > 0:
                s.spec_probe_at = self.decode_steps
                s.spec_miss = 0 if d else s.spec_miss + 1
            any_draft = any_draft or bool(d)
            plan.append((s, s.request, s.position, d))
        if not any_draft:
            return False
        self._spec_round(plan)
        return True

    def _spec_round(self, plan: list) -> None:
        """Dispatch one verify forward for the whole batch and process it
        SYNCHRONOUSLY (the next round's drafts depend on these tokens).
        Every live lane advances at least one token — lanes with no draft
        this round ride along as a plain decode step (draft_len 0)."""
        gmax = max(len(d) for _, _, _, d in plan)
        K = next(b for b in self._spec_buckets if b >= gmax)
        if self.paged:
            # pages must cover the whole verify write span [p, p+K]; a lane
            # the pool can't cover fails with backpressure, the rest verify
            kept = []
            for s, r, p, d in plan:
                try:
                    self._ensure_lane_pages(
                        s, min(p + K, self.max_seq - 2), serving=bool(r.id)
                    )
                    kept.append((s, r, p, d))
                except EngineOverloaded as e:
                    self._fail_item(r, e)
                    self._abandon_slot(s, rollback=True)
            plan = kept
            if not plan:
                return
        drafts = np.zeros((self.max_batch, K), dtype=np.int32)
        dlen = np.zeros((self.max_batch,), dtype=np.int32)
        for s, _, _, d in plan:
            if d:
                drafts[s.idx, : len(d)] = d
                dlen[s.idx] = len(d)
        self._rng, key = jax.random.split(self._rng)
        emitted_dev, count_dev, self._dtok, self._dpos, self.cache = (
            self._verify_fn(K)(
                self.params,
                self.cache,
                *self._bt_arg(),
                self._dtok,
                self._dpos,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                jnp.asarray(drafts),
                jnp.asarray(dlen),
                key,
            )
        )
        emitted = np.asarray(emitted_dev)  # sync readback: spec rounds don't pipeline
        count = np.asarray(count_dev)
        self.host_syncs_total += 1
        end = time.monotonic()
        self.spec_rounds += 1
        self.spec_verify_hist[K] = self.spec_verify_hist.get(K, 0) + 1
        self.decode_steps += 1
        self._occupancy_sum += len(plan) / self.max_batch
        # the whole k+1-token verify streams the weights ONCE (that is the
        # point of batching the verification) plus each live lane's prefix
        self.hbm_bytes_read += self.param_hbm_bytes + sum(
            (p + K // 2) * self._kv_bytes_per_pos for _, _, p, _ in plan
        )
        eos = self.tokenizer.eos_id
        total_used = 0
        for slot, req, p, d in plan:
            if slot.request is not req:
                continue
            c = int(count[slot.idx])
            l = int(dlen[slot.idx])
            self.spec_drafted += l
            self.spec_accepted += c - 1
            self.spec_rejected += l - (c - 1)
            if l:
                slot.spec_ema = (
                    1 - SPEC_EMA_ALPHA
                ) * slot.spec_ema + SPEC_EMA_ALPHA * ((c - 1) / l)
            outs = emitted[slot.idx]
            remaining = req.max_tokens - len(req.generated)
            used = 0
            hit_eos = False
            for j in range(min(c, remaining)):
                used += 1
                if not req.ignore_eos and int(outs[j]) == eos:
                    hit_eos = True
                    break
            req.generated.extend(int(t) for t in outs[:used])
            req.emit_appended(used)
            req.dispatched += c
            self.tokens_generated += used
            total_used += used
            self.flops_done += used * self.cfg.flops_per_token(p + used // 2)
            finished = hit_eos or len(req.generated) >= req.max_tokens
            if finished and used < c:
                # the used-th token was an ACCEPTED draft — already fed
                # through the model at position p + used
                slot.position = p + used + 1
                slot.dev_position = slot.position
                self._finish(slot, pending_last=False)
            elif finished:
                slot.position = p + c
                slot.dev_position = slot.position
                self._finish(slot, pending_last=True)
            else:
                # KV rewind: rejected drafts left stale rows at positions
                # >= p + c; the next fed token overwrites p + c before any
                # query can attend there, and the position mask hides the
                # rest until the stream grows past them
                slot.position = p + c
                slot.dev_position = slot.position
                slot.last_used = end
                if self.paged and slot.psess is not None:
                    # rewind = page-tail truncation: pages holding ONLY
                    # rejected-draft garbage return to the pool right now
                    slot.psess.position = slot.position
                    self._truncate_session_pages(slot.psess)
        if self._last_decode_end is not None and total_used:
            self.itl_ms_recent.append(
                1000 * (end - self._last_decode_end) / total_used
            )
        self._last_decode_end = end

    def _drain_readbacks(self, block: bool) -> None:
        """Process landed readbacks in FIFO order. An entry is forced to
        completion when ``block`` asks for one (idle drain) or whenever the
        queue is deeper than the pipeline depth — the queue must NEVER grow
        past depth+1, or every response is delivered queue-length × chunk
        wall LATE. (Round-5 hardware run: one forced drain per iteration
        while prefill turns appended two entries grew the queue to ~40 —
        admission was 160 ms but TTFT read 6 s, all of it delivery lag.
        The non-blocking is_ready() path never fires on the axon tunnel,
        which can't poll readiness, so the length bound is the only
        effective backpressure there.)

        Forced waits are ADMISSION-AWARE (_wait_admitting): while the oldest
        entry's value crosses the device boundary, the submit queue keeps
        being polled and a newcomer's first prefill chunk is dispatched the
        moment it arrives — dispatches are async, so the device pipelines
        the prefill behind the in-flight decode chunk while the host keeps
        waiting. (The round-5 ~180 ms admission p50 was exactly this wait:
        one full chunk wall between queue polls.)"""
        # (Eager out-of-band delivery of first-token entries was tried and
        # reverted: it blocks the worker on an extra fetch per prefill for
        # a TTFT change inside run-to-run noise, at ~7% decode throughput.)
        while self._readbacks:
            entry = self._readbacks[0]
            arr = entry[3] if entry[0] == "first" else entry[2]
            if not (block or len(self._readbacks) > self._PIPELINE_DEPTH):
                try:
                    if not arr.is_ready():
                        return
                except Exception:
                    return  # readiness not pollable: wait for a forced drain
            elif self.adaptive_decode:
                # adaptive_decode=False is the FIXED-CADENCE baseline
                # scheduler (A/B measurable: scripts/bench_admission.py) —
                # it hard-blocks in processing like the round-5 engine did
                self._wait_admitting(arr)
                if self._sentinel:
                    return
            self._readbacks.popleft()
            if entry[0] == "first":
                self._process_first(entry)
            elif entry[0] == "fused":
                self._process_fused(entry)
            else:
                self._process_chunk(entry)
            block = False

    def _wait_admitting(self, arr) -> None:
        """Forced-drain wait that keeps admitting: poll the submit queue
        while the readback completes, and dispatch a fresh arrival's FIRST
        prefill chunk immediately (later chunks ride the normal interleave).
        Waiting happens ON the queue (get with a small timeout), so an
        enqueue wakes the worker instantly. Backends whose arrays can't
        poll readiness get one admission pass, then fall back to the hard
        block inside processing."""
        while not self._sentinel:
            self._pump_queue(0.0)
            if self._sentinel:
                return
            if self._waiting:
                self._admit_waiting()
            while any(
                s.request is not None
                and s.pending_prompt
                and s.request.prefill_started_at is None
                for s in self.slots
            ):
                try:
                    self._prefill_tick()
                except Exception as e:
                    # same per-request isolation as the main loop's tick
                    self._note_error(e)
                    slot = self._prefilling_slot
                    if slot is not None and slot.request is not None:
                        self._fail_item(slot.request, _as_prefill_failure(e))
                        self._reset_slot(slot)
                    self._ensure_device_state()
                finally:
                    self._prefilling_slot = None
            try:
                if arr.is_ready():
                    return
            except Exception:
                return  # not pollable: processing's np.asarray blocks instead
            try:
                item = self._queue.get(timeout=0.001)
            except queue.Empty:
                continue
            if item is None:
                self._sentinel = True
                return
            self._waiting.append(item)

    def _process_first(self, entry) -> None:
        _, slot, req, first, _ = entry
        if slot.request is not req:
            return  # request failed/superseded while the copy was in flight
        first_id = int(np.asarray(first)[0])
        self.host_syncs_total += 1
        now = time.monotonic()
        req.ttft_ms = 1000 * (now - req.submitted_at)
        self.ttft_ms_recent.append(req.ttft_ms)
        # the other two TTFT phases (queue-wait lands at prefill start):
        # prefill span and the readback tail after first-token injection
        if req.prefill_started_at is not None and req.prefill_done_at is not None:
            self.prefill_ms_recent.append(
                1000 * (req.prefill_done_at - req.prefill_started_at)
            )
            self.first_readback_ms_recent.append(1000 * (now - req.prefill_done_at))
        req.generated.append(first_id)
        req.emit_appended(1)
        self.tokens_generated += 1
        if len(req.generated) >= req.max_tokens or (
            not req.ignore_eos and first_id == self.tokenizer.eos_id
        ):
            # first token not yet in KV: carried into the next turn's prompt
            self._finish(slot, pending_last=True)

    def _process_chunk(self, entry) -> None:
        _, snapshot, toks_dev, _ = entry
        toks = np.asarray(toks_dev)  # [chunk, B]
        self.host_syncs_total += 1
        chunk = toks.shape[0]
        # ITL = wall time between consecutive chunk completions (including
        # any interleaved prefill chunk) per generated token
        end = time.monotonic()
        if self._last_decode_end is not None:
            self.itl_ms_recent.append(1000 * (end - self._last_decode_end) / chunk)
        self._last_decode_end = end
        eos = self.tokenizer.eos_id
        for slot, req, start in snapshot:
            if slot.request is not req:
                continue  # finished in an earlier (lagged) entry
            if not req.generated:
                # first token's readback hasn't been processed yet (it sits
                # later in the FIFO)? cannot happen: FIFO order guarantees
                # the "first" entry precedes every chunk that continues it
                continue
            outs = toks[:, slot.idx]
            remaining = req.max_tokens - len(req.generated)
            used = 0
            hit_eos = False
            for j in range(min(chunk, remaining)):
                used += 1
                if not req.ignore_eos and int(outs[j]) == eos:
                    hit_eos = True
                    break
            req.generated.extend(int(t) for t in outs[:used])
            req.emit_appended(used)
            self.tokens_generated += used
            # useful decode FLOPs only: overshoot tokens and parked lanes
            # are real compute but wasted — MFU should show that, not hide it
            self.flops_done += used * self.cfg.flops_per_token(start + used // 2)
            finished = hit_eos or len(req.generated) >= req.max_tokens
            if finished and used < chunk:
                # chunk overshot: the used-th token was already fed at
                # position start+used; later writes overwrite the overshoot
                slot.position = start + used + 1
                self._finish(slot, pending_last=False)
            elif finished:
                slot.position = start + chunk
                self._finish(slot, pending_last=True)
            else:
                slot.position = start + chunk

    def _process_fused(self, entry) -> None:  # atp: hot
        """Process one fused loop's packed readback — the loop's ONE host
        sync. The host rescans the emitted tokens against its own remaining
        budget and EOS policy (the same scan _process_chunk runs), so stale
        lanes and mid-flight aborts resolve identically in both modes; the
        device's finish reasons are trusted only for device-state
        bookkeeping. A finished lane parked in-loop, so its finishing token
        was never fed: ``pending_last=True`` for every fused finish, and
        slot.position lands at start+used (no overshoot feed to roll back)."""
        _, snapshot, packed_dev, chunk, _ = entry
        cap_rows = self._fused_cap + 1
        # [cap_rows+5, B]: tokens / counts / reasons / steps / nacc / ndr
        packed = np.asarray(packed_dev)
        self.host_syncs_total += 1
        steps = int(packed[cap_rows + 2, 0])
        self.fused_steps_total += steps
        if steps < chunk:
            self.fused_early_exits_total += 1
            self.fused_exit_reason_hist["early_all_finished"] = (
                self.fused_exit_reason_hist.get("early_all_finished", 0) + 1
            )
        else:
            self.fused_exit_reason_hist["limit"] = (
                self.fused_exit_reason_hist.get("limit", 0) + 1
            )
        end = time.monotonic()
        # ITL per TOKEN, not per iteration: in-loop spec can emit several
        # tokens per iteration, and the bench compares fused vs unfused on
        # token cadence. The deepest lane's emission count is the loop's
        # token depth; a loop whose lanes all went stale falls back to the
        # iteration count.
        depth = max(
            (int(packed[cap_rows, s.idx]) for s, r, _, _ in snapshot if s.request is r),
            default=0,
        ) or steps
        if self._last_decode_end is not None and depth:
            self.itl_ms_recent.append(1000 * (end - self._last_decode_end) / depth)
        self._last_decode_end = end
        # HBM accounting happens here (not at dispatch) because the
        # executed step count is data-dependent: weights stream once per
        # while_loop iteration actually run, plus each lane's KV prefix
        self.hbm_bytes_read += steps * self.param_hbm_bytes + sum(
            steps * (p + steps // 2) * self._kv_bytes_per_pos for _, _, p, _ in snapshot
        )
        eos = self.tokenizer.eos_id
        for slot, req, start, _adv in snapshot:
            if slot.request is not req:
                continue  # finished/aborted in an earlier (lagged) entry
            if not req.generated:
                continue  # FIFO order puts the "first" entry before any loop
            cnt = int(packed[cap_rows, slot.idx])
            reason = int(packed[cap_rows + 1, slot.idx])
            self.inloop_spec_accepted += int(packed[cap_rows + 3, slot.idx])
            self.inloop_spec_drafted += int(packed[cap_rows + 4, slot.idx])
            outs = packed[:, slot.idx][:cnt]
            remaining = req.max_tokens - len(req.generated)
            used = 0
            hit_eos = False
            for j in range(min(cnt, remaining)):
                used += 1
                if not req.ignore_eos and int(outs[j]) == eos:
                    hit_eos = True
                    break
            req.generated.extend(int(t) for t in outs[:used])
            req.emit_appended(used)
            self.tokens_generated += used
            self.flops_done += used * self.cfg.flops_per_token(start + used // 2)
            finished = hit_eos or len(req.generated) >= req.max_tokens
            if finished:
                # the host scan is AUTHORITATIVE for budget finishes (the
                # device only ever declares EOS; cap-hit lanes froze with
                # reason 0). An EOS finish never fed its token (in-loop
                # park) and a budget finish froze before feeding past its
                # cap: pending_last=True either way, position at start+used
                slot.position = start + used
                self._finish(slot, pending_last=True)
            elif reason != 0:
                # defensive: the device parked a lane the host scan wants
                # to keep (cannot happen while ignore_eos policies agree —
                # but a parked live lane would decode garbage at scratch
                # forever, so re-point it at its last token explicitly)
                slot.position = start + used
                slot.dev_position = slot.position
                self._inject_lane(
                    slot.idx,
                    jnp.int32(int(outs[used - 1])),
                    slot.position,
                    req.temperature,
                    req.top_k,
                    req.top_p,
                )
            else:
                # live (or frozen-at-cap) lane: dev_position was advanced by
                # the budget upper bound at dispatch; settle it to the REAL
                # device position (start + cnt) plus the upper bounds of any
                # still-in-flight loops that include this lane
                slot.position = start + used
                pending = sum(
                    adv2
                    for e in self._readbacks
                    if e[0] == "fused"
                    for s2, r2, _p2, adv2 in e[1]
                    if s2 is slot and r2 is req
                )
                slot.dev_position = start + cnt + pending


def _resolve(future: asyncio.Future, result: dict) -> None:
    if not future.done():
        future.set_result(result)


def _resolve_value(future: asyncio.Future, value) -> None:
    if not future.done():
        future.set_result(value)


def _reject(future: asyncio.Future, error: Exception) -> None:
    if not future.done():
        # EngineOverloaded covers worker-side PagePoolExhausted: pool
        # backpressure must reach the serve layer typed (429), not be
        # laundered into a generic 500. PrefillFailed must survive for the
        # same reason: the serve layer marks its 500 poisoned so the proxy
        # charges the tightened dead-letter budget instead of archiving it
        if isinstance(
            error, (EngineShutdown, RequestAborted, EngineOverloaded, PrefillFailed)
        ):
            future.set_exception(error)  # callers can catch the type
        else:
            future.set_exception(RuntimeError(f"engine worker error: {error}"))
