"""NativeStore — ctypes binding over the C++ store (native/store.cc).

Drop-in Store implementation backed by the native layer, giving (1) GIL-free
access for the C++ data plane, whose proxy threads journal requests into the
same store object, and (2) durability across daemon restarts via the AOF —
the role Redis persistence plays for the reference's Go server (SURVEY.md
§2.2). Wire encoding is defined in native/common.h; opcode numbers here must
stay in sync with the ``Op`` enum there.
"""

from __future__ import annotations

import ctypes
import struct
import threading
from typing import Any, Callable

from .. import faults
from ..native import load
from .base import Store, Subscription, _to_bytes

# Opcodes — mirror native/common.h enum Op.
OP_SET = 1
OP_GET = 2
OP_DEL = 3
OP_EXISTS = 4
OP_KEYS = 5
OP_EXPIRE = 6
OP_TTL = 7
OP_SADD = 8
OP_SREM = 9
OP_SMEMBERS = 10
OP_RPUSH = 11
OP_LPUSH = 12
OP_LREM = 13
OP_LRANGE = 14
OP_LLEN = 15
OP_LTRIM = 16
OP_ZADD = 17
OP_ZRANGEBYSCORE = 18
OP_ZREMRANGEBYSCORE = 19
OP_ZCARD = 20
OP_HSET = 21
OP_HINCRBY = 22
OP_HGETALL = 23
OP_PUBLISH = 24
OP_FLUSH = 25
OP_PIPELINE = 26
OP_AUTH = 27

RESP_OK = 0
RESP_ERR = 1
RESP_NIL = 2


def encode_request(op: int, args: list[bytes]) -> bytes:
    out = [struct.pack("<BI", op, len(args))]
    for a in args:
        out.append(struct.pack("<I", len(a)))
        out.append(a)
    return b"".join(out)


def decode_response(buf: bytes) -> tuple[int, list[bytes]]:
    status = buf[0]
    (count,) = struct.unpack_from("<I", buf, 1)
    vals = []
    pos = 5
    for _ in range(count):
        (alen,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        vals.append(buf[pos : pos + alen])
        pos += alen
    return status, vals


class NativeSubscription(Subscription):
    """Subscription backed by the C++ store's queue; get() polls natively
    (GIL released during the ctypes call)."""

    def __init__(self, patterns: tuple[str, ...], store: "NativeStore", sub_id: int):
        super().__init__(patterns, lambda _sub: store._sub_close(sub_id))
        self._store = store
        self._sub_id = sub_id

    def get(self, timeout: float | None = None) -> tuple[str, str] | None:
        deadline = None if timeout is None else (timeout if timeout > 0 else 0)
        # bounded native waits so Ctrl-C / interpreter exit stay responsive
        remaining = deadline
        while True:
            step_ms = 200 if remaining is None else int(min(remaining, 0.2) * 1000)
            got = self._store._sub_poll(self._sub_id, step_ms)
            if got is not None:
                return got
            if remaining is not None:
                remaining -= 0.2
                if remaining <= 0:
                    return None

    def drain(self) -> list[tuple[str, str]]:
        out = []
        while True:
            got = self._store._sub_poll(self._sub_id, 0)
            if got is None:
                return out
            out.append(got)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._store._sub_close(self._sub_id)


class NativeStore(Store):
    def __init__(self, aof_path: str | None = None):
        self._lib = load()
        if self._lib is None:
            from ..native import load_error

            raise RuntimeError(f"native store unavailable: {load_error()}")
        self._handle = self._lib.atpu_store_new(
            aof_path.encode() if aof_path else None
        )
        self._cb_threads: list[tuple[threading.Event, threading.Thread]] = []
        self._closed = False
        self.callback_errors_total = 0  # subscriber-callback failures (logged)
        # CAS serialization: the C++ store has no native compare-and-set
        # opcode, so cas() brackets get+set under this lock. That is atomic
        # for every Python-side caller of cas() on this handle — the journal
        # processing transition, its only user — but NOT against raw native
        # writes from the C++ data plane (which never touches journal
        # status fields).
        self._cas_lock = threading.Lock()
        # in-flight native-call accounting: close() must not free the C++
        # store while any thread is inside a lib call on this handle
        self._inflight = 0
        self._inflight_cv = threading.Condition()

    def _enter(self) -> bool:
        with self._inflight_cv:
            if self._closed:
                return False
            self._inflight += 1
            return True

    def _leave(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._inflight_cv.notify_all()

    # -- command plumbing -------------------------------------------------
    def _cmd(self, op: int, *args: bytes | str) -> tuple[int, list[bytes]]:
        req = encode_request(op, [_to_bytes(a) for a in args])
        resp_ptr = ctypes.POINTER(ctypes.c_uint8)()
        resp_len = ctypes.c_size_t()
        if not self._enter():
            raise RuntimeError("store is closed")
        try:
            self._lib.atpu_cmd(
                self._handle, req, len(req), ctypes.byref(resp_ptr), ctypes.byref(resp_len)
            )
        finally:
            self._leave()
        raw = ctypes.string_at(resp_ptr, resp_len.value)
        self._lib.atpu_free(resp_ptr)
        status, vals = decode_response(raw)
        if status == RESP_ERR:
            msg = vals[0].decode("utf-8", "replace") if vals else "error"
            if msg.startswith("WRONGTYPE"):
                raise TypeError(msg)
            raise ValueError(msg)
        return status, vals

    def _int(self, op: int, *args: bytes | str) -> int:
        _, vals = self._cmd(op, *args)
        return int(vals[0]) if vals else 0

    # -- strings ----------------------------------------------------------
    def set(self, key: str, value: bytes | str, ttl: float | None = None) -> None:
        faults.fire("store.set")
        self._cmd(OP_SET, key, value, "" if ttl is None else repr(float(ttl)))

    def get(self, key: str) -> bytes | None:
        faults.fire("store.get")
        status, vals = self._cmd(OP_GET, key)
        return None if status == RESP_NIL else vals[0]

    def delete(self, *keys: str) -> int:
        if not keys:
            return 0
        return self._int(OP_DEL, *keys)

    def exists(self, key: str) -> bool:
        return self._int(OP_EXISTS, key) == 1

    def keys(self, pattern: str = "*") -> list[str]:
        _, vals = self._cmd(OP_KEYS, pattern)
        return [v.decode("utf-8", "replace") for v in vals]

    def expire(self, key: str, ttl: float) -> bool:
        return self._int(OP_EXPIRE, key, repr(float(ttl))) == 1

    def ttl(self, key: str) -> float | None:
        status, vals = self._cmd(OP_TTL, key)
        return None if status == RESP_NIL else float(vals[0])

    def cas(
        self,
        key: str,
        expected: bytes | str | None,
        new: bytes | str,
        ttl: float | None = None,
    ) -> bool:
        faults.fire("store.cas")
        exp = None if expected is None else _to_bytes(expected)
        with self._cas_lock:
            if self.get(key) != exp:
                return False
            if ttl is None:
                ttl = self.ttl(key)
            self.set(key, new, ttl=ttl)
            return True

    # -- sets -------------------------------------------------------------
    def sadd(self, key: str, *members: str) -> int:
        return self._int(OP_SADD, key, *members)

    def srem(self, key: str, *members: str) -> int:
        return self._int(OP_SREM, key, *members)

    def smembers(self, key: str) -> set[str]:
        _, vals = self._cmd(OP_SMEMBERS, key)
        return {v.decode("utf-8", "replace") for v in vals}

    # -- lists ------------------------------------------------------------
    def rpush(self, key: str, *values: bytes | str) -> int:
        return self._int(OP_RPUSH, key, *values)

    def lpush(self, key: str, *values: bytes | str) -> int:
        return self._int(OP_LPUSH, key, *values)

    def lrem(self, key: str, count: int, value: bytes | str) -> int:
        return self._int(OP_LREM, key, str(count), value)

    def lrange(self, key: str, start: int, stop: int) -> list[bytes]:
        _, vals = self._cmd(OP_LRANGE, key, str(start), str(stop))
        return vals

    def llen(self, key: str) -> int:
        return self._int(OP_LLEN, key)

    def ltrim(self, key: str, start: int, stop: int) -> None:
        self._cmd(OP_LTRIM, key, str(start), str(stop))

    # -- sorted sets ------------------------------------------------------
    def zadd(self, key: str, score: float, member: bytes | str) -> None:
        self._cmd(OP_ZADD, key, repr(float(score)), member)

    def zrangebyscore(
        self, key: str, min_score: float, max_score: float, limit: int | None = None
    ) -> list[bytes]:
        _, vals = self._cmd(
            OP_ZRANGEBYSCORE,
            key,
            repr(float(min_score)),
            repr(float(max_score)),
            "" if limit is None else str(limit),
        )
        return vals

    def zremrangebyscore(self, key: str, min_score: float, max_score: float) -> int:
        return self._int(
            OP_ZREMRANGEBYSCORE, key, repr(float(min_score)), repr(float(max_score))
        )

    def zcard(self, key: str) -> int:
        return self._int(OP_ZCARD, key)

    # -- hashes -----------------------------------------------------------
    def hset(self, key: str, field: str, value: bytes | str) -> None:
        self._cmd(OP_HSET, key, field, value)

    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        return self._int(OP_HINCRBY, key, field, str(amount))

    def hgetall(self, key: str) -> dict[str, bytes]:
        _, vals = self._cmd(OP_HGETALL, key)
        return {
            vals[i].decode("utf-8", "replace"): vals[i + 1]
            for i in range(0, len(vals), 2)
        }

    # -- pub/sub ----------------------------------------------------------
    def publish(self, channel: str, message: str) -> int:
        msg = _to_bytes(message)
        if not self._enter():
            return 0
        try:
            return self._lib.atpu_publish(self._handle, channel.encode(), msg, len(msg))
        finally:
            self._leave()

    def psubscribe(self, *patterns: str) -> Subscription:
        buf = struct.pack("<I", len(patterns))
        for p in patterns:
            pb = p.encode()
            buf += struct.pack("<I", len(pb)) + pb
        if not self._enter():
            raise RuntimeError("store is closed")
        try:
            sub_id = self._lib.atpu_subscribe(self._handle, buf, len(buf))
        finally:
            self._leave()
        return NativeSubscription(tuple(patterns), self, sub_id)

    def _sub_poll(self, sub_id: int, timeout_ms: int) -> tuple[str, str] | None:
        if not self._enter():
            return None
        try:
            resp_ptr = ctypes.POINTER(ctypes.c_uint8)()
            resp_len = ctypes.c_size_t()
            rc = self._lib.atpu_sub_poll(
                self._handle, sub_id, timeout_ms, ctypes.byref(resp_ptr), ctypes.byref(resp_len)
            )
        finally:
            self._leave()
        if rc != 1:
            return None
        raw = ctypes.string_at(resp_ptr, resp_len.value)
        self._lib.atpu_free(resp_ptr)
        (chan_len,) = struct.unpack_from("<I", raw, 0)
        channel = raw[4 : 4 + chan_len].decode("utf-8", "replace")
        message = raw[4 + chan_len :].decode("utf-8", "replace")
        return channel, message

    def _sub_close(self, sub_id: int) -> None:
        if self._enter():
            try:
                self._lib.atpu_sub_close(self._handle, sub_id)
            finally:
                self._leave()

    def on_message(self, pattern: str, callback: Callable[[str, str], None]) -> Callable[[], None]:
        sub = self.psubscribe(pattern)
        stop = threading.Event()

        def poller() -> None:
            while not stop.is_set():
                got = self._sub_poll(sub._sub_id, 200)
                if got is not None:
                    try:
                        callback(*got)
                    except Exception as e:
                        # subscriber bugs must not kill the poller — but a
                        # silently-eaten callback error once hid a broken
                        # watcher for a whole soak: count it and say so
                        self.callback_errors_total += 1
                        print(
                            f"[store] subscriber callback failed for "
                            f"{pattern!r}: {type(e).__name__}: {e}",
                            flush=True,
                        )

        t = threading.Thread(target=poller, daemon=True, name=f"store-sub-{pattern}")
        t.start()
        self._cb_threads.append((stop, t))

        def unregister() -> None:
            stop.set()
            sub.close()

        return unregister

    # -- lifecycle ---------------------------------------------------------
    def flush(self) -> None:
        self._cmd(OP_FLUSH)

    def aof_flush(self) -> None:
        faults.fire("store.aof_flush")
        if self._enter():
            try:
                self._lib.atpu_aof_flush(self._handle)
            finally:
                self._leave()

    @property
    def handle(self) -> int:
        """Raw C handle, used to hand the same store to the data plane."""
        return self._handle

    def close(self) -> None:
        with self._inflight_cv:
            if self._closed:
                return
            self._closed = True  # new native calls are refused from here on
        for stop, _t in self._cb_threads:
            stop.set()
        for _stop, t in self._cb_threads:
            t.join(timeout=2.0)
        # wait for every thread to leave native code; if any straggler
        # remains (e.g. a blocked subscriber), deliberately LEAK the C++
        # store rather than free memory another thread is using
        with self._inflight_cv:
            self._inflight_cv.wait_for(lambda: self._inflight == 0, timeout=5.0)
            if self._inflight != 0:
                return
        self._lib.atpu_aof_flush(self._handle)
        self._lib.atpu_store_free(self._handle)

    def __del__(self) -> None:  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
