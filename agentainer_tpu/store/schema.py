"""Key schema — kept 1:1 with the reference's Redis data model (SURVEY.md §2.2).

Reference writers/readers, for parity auditing:
- ``agent:{id}``               JSON agent record   (agent.go:510-530)
- ``agents:list``              set of agent IDs    (agent.go:525)
- ``agent:{id}:status``        legacy status key   (state_sync.go:203-206)
- ``agent:{id}:requests:{rid}``JSON request, 24h   (requests.go:100-107)
- ``agent:{id}:requests:pending|completed|failed`` lists (requests.go:111-261)
- ``health:{id}``              JSON health, 24h    (monitor.go:267-270)
- ``metrics:current:{id}``     JSON, 1h TTL        (collector.go:308)
- ``metrics:history:{id}``     zset by ts, 24h     (collector.go:313-321)
- ``logs:entries`` / ``audit:entries``  zsets, 7d  (logger.go:340-348)
- channel ``agent:status:{id}``         pub/sub    (state_sync.go:311-317)

TPU-native additions (no reference counterpart):
- ``agent:{id}:kvcache:{session}``  serialized KV-cache pages for crash-resume
- ``agent:{id}:conversations``      conversation turns (was app-side in the
  reference's example agents, app.py:50-68 — here it is a framework feature)
- ``slices:allocations``            chip→agent placement map of the scheduler
"""

from __future__ import annotations

# Retention, matching the reference's envelope (BASELINE.md).
REQUEST_TTL_S = 24 * 3600  # requests.go:106
HEALTH_TTL_S = 24 * 3600  # monitor.go:267-270
METRICS_CURRENT_TTL_S = 3600  # collector.go:308
METRICS_HISTORY_S = 24 * 3600  # collector.go:313-321
LOG_RETENTION_S = 7 * 24 * 3600  # logger.go:346-348


class Keys:
    AGENTS_LIST = "agents:list"
    LOGS = "logs:entries"
    AUDIT = "audit:entries"
    LOG_STREAM = "logs:stream"
    SLICE_ALLOCATIONS = "slices:allocations"

    @staticmethod
    def agent(agent_id: str) -> str:
        return f"agent:{agent_id}"

    @staticmethod
    def agent_status(agent_id: str) -> str:
        return f"agent:{agent_id}:status"

    @staticmethod
    def request(agent_id: str, request_id: str) -> str:
        return f"agent:{agent_id}:requests:{request_id}"

    @staticmethod
    def pending(agent_id: str) -> str:
        return f"agent:{agent_id}:requests:pending"

    @staticmethod
    def completed(agent_id: str) -> str:
        return f"agent:{agent_id}:requests:completed"

    @staticmethod
    def failed(agent_id: str) -> str:
        return f"agent:{agent_id}:requests:failed"

    @staticmethod
    def expired(agent_id: str) -> str:
        """Dead-letter list for requests whose deadline passed before they
        could be served — work nobody is waiting for anymore. Distinct from
        ``failed`` (which implies the engine tried and errored) so operators
        can requeue outage victims without replaying genuinely bad requests."""
        return f"agent:{agent_id}:requests:expired"

    @staticmethod
    def health(agent_id: str) -> str:
        return f"health:{agent_id}"

    @staticmethod
    def metrics_current(agent_id: str) -> str:
        return f"metrics:current:{agent_id}"

    @staticmethod
    def metrics_history(agent_id: str) -> str:
        return f"metrics:history:{agent_id}"

    @staticmethod
    def status_channel(agent_id: str) -> str:
        return f"agent:status:{agent_id}"

    STATUS_CHANNEL_PATTERN = "agent:status:*"
    PENDING_PATTERN = "agent:*:requests:pending"

    @staticmethod
    def internal_token(agent_id: str) -> str:
        """Per-engine store-API token. Deliberately OUTSIDE the agent:{id}:*
        namespace so engines cannot read each other's tokens through the
        store endpoint."""
        return f"internal:token:{agent_id}"

    @staticmethod
    def conversations(agent_id: str) -> str:
        """Legacy shared conversation list (all sessions interleaved);
        new turns land on per-session keys (conversations_session)."""
        return f"agent:{agent_id}:conversations"

    @staticmethod
    def conversations_session(agent_id: str, session: str) -> str:
        return f"agent:{agent_id}:conversations:{session}"

    @staticmethod
    def conversations_pattern(agent_id: str) -> str:
        """Matches the per-session lists only, not the legacy shared key."""
        return f"agent:{agent_id}:conversations:*"

    @staticmethod
    def agent_metrics_hash(agent_id: str) -> str:
        return f"agent:{agent_id}:metrics"

    @staticmethod
    def replica_lease(agent_id: str, engine_id: str) -> str:
        """Heartbeat lease for one engine replica: a JSON doc written with
        a TTL by the replica monitor. Lease age drives the per-replica
        ALIVE/SUSPECT/DEAD state machine; an expired (absent) lease is the
        durable evidence a replica stopped answering."""
        return f"agent:{agent_id}:replica:{engine_id}:lease"

    @staticmethod
    def replica_lease_pattern(agent_id: str) -> str:
        return f"agent:{agent_id}:replica:*:lease"

    @staticmethod
    def kvcache(agent_id: str, session_id: str) -> str:
        return f"agent:{agent_id}:kvcache:{session_id}"

    @staticmethod
    def kvcache_pattern(agent_id: str) -> str:
        return f"agent:{agent_id}:kvcache:*"
