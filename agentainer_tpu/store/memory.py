"""In-memory Store — the default backing for a single control-plane daemon.

The reference externalizes all state to a Redis 7 sidecar so the Go server can
restart without losing agent records (reference scripts/start-server.sh:12-19,
docker-compose.yml). On a TPU-VM the control plane and engines share one host,
so the default store is in-process; durability across daemon restarts comes
from the snapshot/backup plane (manager/backup.py), and a real Redis can still
be swapped in behind the same interface when available.

Semantics follow Redis where it matters: lazy TTL expiry, ``lrem`` counted
removal (reference requests.go:171 uses LREM pending 1 id), sorted-set
score-range queries for metrics/log history (reference collector.go:174-200,
logger.go:201-246), and glob-pattern pub/sub.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Callable

from .. import faults
from .base import Store, Subscription, _to_bytes


class _ZSet:
    __slots__ = ("scores",)

    def __init__(self) -> None:
        self.scores: dict[bytes, float] = {}


class MemoryStore(Store):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: dict[str, object] = {}
        self._expiry: dict[str, float] = {}
        self._subs: list[Subscription] = []
        self._callbacks: list[tuple[str, Callable[[str, str], None]]] = []
        self.callback_errors_total = 0  # subscriber-callback failures (logged)

    # -- internals -------------------------------------------------------
    def _live(self, key: str) -> bool:
        exp = self._expiry.get(key)
        if exp is not None and time.time() >= exp:
            self._data.pop(key, None)
            self._expiry.pop(key, None)
            return False
        return key in self._data

    def _typed(self, key: str, typ: type, create: bool = False):
        if self._live(key):
            val = self._data[key]
            if not isinstance(val, typ):
                raise TypeError(f"key {key!r} holds {type(val).__name__}, wanted {typ.__name__}")
            return val
        if create:
            val = typ()
            self._data[key] = val
            self._expiry.pop(key, None)
            return val
        return None

    # -- strings ---------------------------------------------------------
    def set(self, key: str, value: bytes | str, ttl: float | None = None) -> None:
        faults.fire("store.set")  # outside the lock: a delay must not block readers
        with self._lock:
            self._data[key] = _to_bytes(value)
            if ttl is None:
                self._expiry.pop(key, None)
            else:
                self._expiry[key] = time.time() + ttl

    def get(self, key: str) -> bytes | None:
        faults.fire("store.get")
        with self._lock:
            if not self._live(key):
                return None
            val = self._data[key]
            if not isinstance(val, bytes):
                raise TypeError(f"key {key!r} holds {type(val).__name__}, wanted bytes")
            return val

    def delete(self, *keys: str) -> int:
        with self._lock:
            n = 0
            for key in keys:
                if self._live(key):
                    n += 1
                self._data.pop(key, None)
                self._expiry.pop(key, None)
            return n

    def exists(self, key: str) -> bool:
        with self._lock:
            return self._live(key)

    def keys(self, pattern: str = "*") -> list[str]:
        with self._lock:
            return [k for k in list(self._data) if self._live(k) and fnmatch.fnmatchcase(k, pattern)]

    def expire(self, key: str, ttl: float) -> bool:
        with self._lock:
            if not self._live(key):
                return False
            self._expiry[key] = time.time() + ttl
            return True

    def ttl(self, key: str) -> float | None:
        with self._lock:
            if not self._live(key):
                return None
            exp = self._expiry.get(key)
            return None if exp is None else max(0.0, exp - time.time())

    def cas(
        self,
        key: str,
        expected: bytes | str | None,
        new: bytes | str,
        ttl: float | None = None,
    ) -> bool:
        faults.fire("store.cas")
        # under the SAME lock every other mutation takes: atomic against
        # concurrent set/delete, not just against other cas callers
        with self._lock:
            cur = self.get(key)
            exp = None if expected is None else _to_bytes(expected)
            if cur != exp:
                return False
            if ttl is None:
                ttl = self.ttl(key)
            self.set(key, new, ttl=ttl)
            return True

    # -- sets ------------------------------------------------------------
    def sadd(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._typed(key, set, create=True)
            before = len(s)
            s.update(members)
            return len(s) - before

    def srem(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._typed(key, set)
            if s is None:
                return 0
            n = 0
            for m in members:
                if m in s:
                    s.discard(m)
                    n += 1
            if not s:
                self.delete(key)
            return n

    def smembers(self, key: str) -> set[str]:
        with self._lock:
            s = self._typed(key, set)
            return set(s) if s else set()

    # -- lists -----------------------------------------------------------
    def rpush(self, key: str, *values: bytes | str) -> int:
        with self._lock:
            lst = self._typed(key, list, create=True)
            lst.extend(_to_bytes(v) for v in values)
            return len(lst)

    def lpush(self, key: str, *values: bytes | str) -> int:
        with self._lock:
            lst = self._typed(key, list, create=True)
            for v in values:
                lst.insert(0, _to_bytes(v))
            return len(lst)

    def lrem(self, key: str, count: int, value: bytes | str) -> int:
        with self._lock:
            lst = self._typed(key, list)
            if not lst:
                return 0
            val = _to_bytes(value)
            removed = 0
            if count >= 0:
                limit = count if count > 0 else len(lst)
                out = []
                for item in lst:
                    if item == val and removed < limit:
                        removed += 1
                    else:
                        out.append(item)
            else:
                limit = -count
                out = []
                for item in reversed(lst):
                    if item == val and removed < limit:
                        removed += 1
                    else:
                        out.append(item)
                out.reverse()
            self._data[key] = out
            if not out:
                self.delete(key)
            return removed

    def lrange(self, key: str, start: int, stop: int) -> list[bytes]:
        with self._lock:
            lst = self._typed(key, list)
            if not lst:
                return []
            # Redis LRANGE: stop is inclusive; -1 means end of list.
            n = len(lst)
            if start < 0:
                start = max(0, n + start)
            if stop < 0:
                stop = n + stop
            return list(lst[start : stop + 1])

    def llen(self, key: str) -> int:
        with self._lock:
            lst = self._typed(key, list)
            return len(lst) if lst else 0

    def ltrim(self, key: str, start: int, stop: int) -> None:
        with self._lock:
            lst = self._typed(key, list)
            if not lst:
                return
            n = len(lst)
            if start < 0:
                start = max(0, n + start)
            if stop < 0:
                stop = n + stop
            kept = lst[start : stop + 1]
            if kept:
                self._data[key] = kept
            else:
                self.delete(key)

    # -- sorted sets -----------------------------------------------------
    def zadd(self, key: str, score: float, member: bytes | str) -> None:
        with self._lock:
            z = self._typed(key, _ZSet, create=True)
            z.scores[_to_bytes(member)] = float(score)

    def _zsorted(self, z: _ZSet) -> list[tuple[bytes, float]]:
        return sorted(z.scores.items(), key=lambda kv: (kv[1], kv[0]))

    def zrangebyscore(
        self, key: str, min_score: float, max_score: float, limit: int | None = None
    ) -> list[bytes]:
        with self._lock:
            z = self._typed(key, _ZSet)
            if not z:
                return []
            out = [m for m, s in self._zsorted(z) if min_score <= s <= max_score]
            return out if limit is None else out[:limit]

    def zremrangebyscore(self, key: str, min_score: float, max_score: float) -> int:
        with self._lock:
            z = self._typed(key, _ZSet)
            if not z:
                return 0
            doomed = [m for m, s in z.scores.items() if min_score <= s <= max_score]
            for m in doomed:
                del z.scores[m]
            if not z.scores:
                self.delete(key)
            return len(doomed)

    def zcard(self, key: str) -> int:
        with self._lock:
            z = self._typed(key, _ZSet)
            return len(z.scores) if z else 0

    # -- hashes ----------------------------------------------------------
    def hset(self, key: str, field: str, value: bytes | str) -> None:
        with self._lock:
            h = self._typed(key, dict, create=True)
            h[field] = _to_bytes(value)

    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        with self._lock:
            h = self._typed(key, dict, create=True)
            cur = int(h.get(field, b"0"))
            cur += amount
            h[field] = str(cur).encode()
            return cur

    def hgetall(self, key: str) -> dict[str, bytes]:
        with self._lock:
            h = self._typed(key, dict)
            return dict(h) if h else {}

    # -- pub/sub ---------------------------------------------------------
    def publish(self, channel: str, message: str) -> int:
        with self._lock:
            subs = list(self._subs)
            cbs = list(self._callbacks)
        n = 0
        for sub in subs:
            if not sub.closed and any(fnmatch.fnmatchcase(channel, p) for p in sub.patterns):
                sub._deliver(channel, message)
                n += 1
        for pattern, cb in cbs:
            if fnmatch.fnmatchcase(channel, pattern):
                try:
                    cb(channel, message)
                    n += 1
                except Exception as e:
                    # subscriber bugs must not break publishers — but they
                    # must be visible (same log-and-count discipline as the
                    # native store's poller)
                    self.callback_errors_total += 1
                    print(
                        f"[store] subscriber callback failed for "
                        f"{pattern!r}: {type(e).__name__}: {e}",
                        flush=True,
                    )
        return n

    def psubscribe(self, *patterns: str) -> Subscription:
        sub = Subscription(tuple(patterns), self._drop_sub)
        with self._lock:
            self._subs.append(sub)
        return sub

    def _drop_sub(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def on_message(self, pattern: str, callback: Callable[[str, str], None]) -> Callable[[], None]:
        entry = (pattern, callback)
        with self._lock:
            self._callbacks.append(entry)

        def unregister() -> None:
            with self._lock:
                if entry in self._callbacks:
                    self._callbacks.remove(entry)

        return unregister

    # -- lifecycle -------------------------------------------------------
    def flush(self) -> None:
        with self._lock:
            self._data.clear()
            self._expiry.clear()
