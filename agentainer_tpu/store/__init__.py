"""State store — the single source of truth for the control plane.

The reference uses Redis for everything: agent records, request journal,
health, metrics, logs, audit, and pub/sub eventing (reference
internal/storage/storage.go:11-97 and the key schema spread across
internal/agent/agent.go:510-592, internal/requests/requests.go:64-275,
internal/health/monitor.go:267-270, pkg/metrics/collector.go:300-322,
internal/logging/logger.go:323-349).

This package defines a Store interface with exactly the operation surface the
framework needs (strings+TTL, sets, lists, sorted sets, hashes, pattern
pub/sub), an in-memory implementation (default — no external Redis required on
a TPU-VM), and an optional native C++ implementation behind the same interface.
The *key schema* is kept 1:1 with the reference (see schema.py) so that the
data model survives the port even though the engine underneath changed.
"""

from .base import Store, Subscription
from .memory import MemoryStore
from .schema import Keys

__all__ = ["Store", "Subscription", "MemoryStore", "Keys", "open_store"]


def open_store(url: str | None = None) -> Store:
    """Open a store from a URL.

    ``mem://`` (default) → in-process MemoryStore;
    ``native://`` → C++ store; ``native:///abs/path.aof`` additionally
    persists every mutation to an append-only file replayed on reopen
    (the durability Redis gave the reference). Falls back to MemoryStore
    if the shared library can't be built;
    ``redis://host:port`` → real Redis, if the ``redis`` package is present
    (it is not baked into the TPU-VM image, so this is gated).
    """
    if not url or url.startswith("mem://"):
        return MemoryStore()
    if url.startswith("native://"):
        aof = url[len("native://") :]
        try:
            from .native import NativeStore

            return NativeStore(aof_path=aof or None)
        except Exception as e:
            if aof:
                # an AOF path is a durability REQUEST: a daemon that
                # believes it has crash-safe state must never silently run
                # on a memory store (VERDICT round-1 weak #7)
                raise RuntimeError(
                    f"native store with AOF durability requested ({url!r}) "
                    f"but unavailable: {e!r}. Refusing to downgrade "
                    "silently — build native/ (make -C native) or pass "
                    "mem:// to explicitly run without durability"
                ) from e
            import logging

            logging.getLogger("agentainer").error(
                "native store unavailable (%s); falling back to the "
                "non-durable MemoryStore (no AOF path was requested)",
                e,
            )
            return MemoryStore()
    if url.startswith("redis://"):
        raise RuntimeError(
            "redis-py is not available in this environment; use mem:// or native://"
        )
    raise ValueError(f"unknown store url: {url}")
