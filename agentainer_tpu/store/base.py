"""Store interface.

The operation surface is the union of Redis commands the reference actually
issues (GET/SET/SETEX/DEL/EXISTS/KEYS, SADD/SREM/SMEMBERS, RPUSH/LREM/LRANGE,
ZADD/ZRANGEBYSCORE/ZREMRANGEBYSCORE, HSET/HINCRBY/HGETALL, PUBLISH/SUBSCRIBE —
see reference internal/storage/storage.go:21-76 and call sites cited in
SURVEY.md §2.2), with two deliberate fixes over the reference:

- ``scan`` replaces unbounded ``KEYS`` scans on the hot replay path
  (reference replay_worker.go:60 uses KEYS every 5s);
- ``psubscribe`` gives real glob-pattern channel matching (the reference
  subscribes to ``agent:status:*`` with a non-pattern SUBSCRIBE, which never
  matches — monitor.go:301, collector.go:326).

Values are ``bytes`` (binary-safe, so KV-cache snapshots can live here too);
``*_json`` helpers cover the common JSON-record case.
"""

from __future__ import annotations

import json
import threading
from abc import ABC, abstractmethod
from collections import deque
from typing import Any, Callable, Iterable, Iterator


def _to_bytes(v: bytes | str) -> bytes:
    return v.encode("utf-8") if isinstance(v, str) else v


class Subscription:
    """A queue-backed subscription to one or more channel patterns.

    ``get``/``drain`` are thread-safe; callers that live on an asyncio loop
    should prefer registering a callback via ``Store.on_message`` instead of
    blocking on a Subscription.
    """

    def __init__(self, patterns: tuple[str, ...], unsubscribe: Callable[["Subscription"], None]):
        self.patterns = patterns
        self._queue: deque[tuple[str, str]] = deque()
        self._cond = threading.Condition()
        self._unsubscribe = unsubscribe
        self.closed = False

    def _deliver(self, channel: str, message: str) -> None:
        with self._cond:
            self._queue.append((channel, message))
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> tuple[str, str] | None:
        """Pop one (channel, message), blocking up to ``timeout`` seconds."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout)
            if self._queue:
                return self._queue.popleft()
            return None

    def drain(self) -> list[tuple[str, str]]:
        with self._cond:
            out = list(self._queue)
            self._queue.clear()
            return out

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._unsubscribe(self)


class Store(ABC):
    """Abstract control-plane state store (Redis-shaped)."""

    # -- strings ---------------------------------------------------------
    @abstractmethod
    def set(self, key: str, value: bytes | str, ttl: float | None = None) -> None: ...

    @abstractmethod
    def get(self, key: str) -> bytes | None: ...

    @abstractmethod
    def delete(self, *keys: str) -> int: ...

    @abstractmethod
    def exists(self, key: str) -> bool: ...

    @abstractmethod
    def keys(self, pattern: str = "*") -> list[str]: ...

    @abstractmethod
    def expire(self, key: str, ttl: float) -> bool: ...

    @abstractmethod
    def ttl(self, key: str) -> float | None:
        """Remaining TTL in seconds, None if no TTL or missing key."""

    def scan(self, pattern: str = "*", batch: int = 512) -> Iterator[str]:
        """Cursor-style iteration; default implementation chunks ``keys``."""
        ks = self.keys(pattern)
        for i in range(0, len(ks), batch):
            yield from ks[i : i + batch]

    # -- sets ------------------------------------------------------------
    @abstractmethod
    def sadd(self, key: str, *members: str) -> int: ...

    @abstractmethod
    def srem(self, key: str, *members: str) -> int: ...

    @abstractmethod
    def smembers(self, key: str) -> set[str]: ...

    # -- lists -----------------------------------------------------------
    @abstractmethod
    def rpush(self, key: str, *values: bytes | str) -> int: ...

    @abstractmethod
    def lpush(self, key: str, *values: bytes | str) -> int: ...

    @abstractmethod
    def lrem(self, key: str, count: int, value: bytes | str) -> int: ...

    @abstractmethod
    def lrange(self, key: str, start: int, stop: int) -> list[bytes]: ...

    @abstractmethod
    def llen(self, key: str) -> int: ...

    @abstractmethod
    def ltrim(self, key: str, start: int, stop: int) -> None: ...

    # -- sorted sets -----------------------------------------------------
    @abstractmethod
    def zadd(self, key: str, score: float, member: bytes | str) -> None: ...

    @abstractmethod
    def zrangebyscore(
        self, key: str, min_score: float, max_score: float, limit: int | None = None
    ) -> list[bytes]: ...

    @abstractmethod
    def zremrangebyscore(self, key: str, min_score: float, max_score: float) -> int: ...

    @abstractmethod
    def zcard(self, key: str) -> int: ...

    # -- hashes ----------------------------------------------------------
    @abstractmethod
    def hset(self, key: str, field: str, value: bytes | str) -> None: ...

    @abstractmethod
    def hincrby(self, key: str, field: str, amount: int = 1) -> int: ...

    @abstractmethod
    def hgetall(self, key: str) -> dict[str, bytes]: ...

    # -- pub/sub ---------------------------------------------------------
    @abstractmethod
    def publish(self, channel: str, message: str) -> int:
        """Publish; returns number of receivers."""

    @abstractmethod
    def psubscribe(self, *patterns: str) -> Subscription:
        """Glob-pattern subscription (the fix for reference monitor.go:301)."""

    @abstractmethod
    def on_message(self, pattern: str, callback: Callable[[str, str], None]) -> Callable[[], None]:
        """Register a callback for a pattern; returns an unregister function.

        Callbacks run on an arbitrary thread (the publisher's for the memory
        store, a poller thread for the native store) and may be delivered
        asynchronously — asyncio consumers should bounce to their loop via
        ``call_soon_threadsafe`` and must not assume delivery-before-return.
        """

    # -- compare-and-set -------------------------------------------------
    def cas(
        self,
        key: str,
        expected: bytes | str | None,
        new: bytes | str,
        ttl: float | None = None,
    ) -> bool:
        """Atomically replace ``key``'s value with ``new`` iff its current
        value equals ``expected`` (``None`` = key must be absent). Returns
        whether the swap happened. This is the primitive the request
        journal's pending→processing transition rides on: two dispatchers
        (proxy + replay tick) racing the same entry must resolve to exactly
        one winner, not two dispatches. The default implementation
        serializes through a per-store lock; subclasses whose backing store
        has a native CAS should override."""
        lock = self.__dict__.get("_cas_lock")
        if lock is None:
            lock = self.__dict__.setdefault("_cas_lock", threading.Lock())
        exp = None if expected is None else _to_bytes(expected)
        with lock:
            cur = self.get(key)
            if cur != exp:
                return False
            if ttl is None:
                # preserve the record's remaining TTL across the swap —
                # a CAS must not silently turn a 24h record permanent
                ttl = self.ttl(key)
            self.set(key, new, ttl=ttl)
            return True

    # -- lifecycle -------------------------------------------------------
    @abstractmethod
    def flush(self) -> None: ...

    def close(self) -> None:  # pragma: no cover - trivial
        pass

    # -- JSON helpers ----------------------------------------------------
    def set_json(self, key: str, obj: Any, ttl: float | None = None) -> None:
        self.set(key, json.dumps(obj, separators=(",", ":")), ttl=ttl)

    def get_json(self, key: str) -> Any | None:
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def lrange_str(self, key: str, start: int, stop: int) -> list[str]:
        return [v.decode("utf-8") for v in self.lrange(key, start, stop)]
