"""TPU hardware envelope: peak FLOPs and HBM bandwidth per device kind.

Used for MFU/MBU accounting in the engine's metrics plane and bench_llm.py
(VERDICT r2 items 1-2: the project had no FLOP model, so MFU could never be
computed). Numbers are public spec-sheet peaks per CHIP; ``jax.devices()``
reports one device per chip on v4+ (v2/v3 report per-core — the two-core
kinds below carry per-core numbers for that reason).

The engine divides its achieved FLOP rate by ``peak_flops × n_devices`` so
a TP-sharded engine is measured against the peak of every chip it spans.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class ChipSpec:
    kind: str
    bf16_flops: float  # peak FLOP/s, bf16 into f32 MXU
    int8_ops: float  # peak OP/s, int8
    hbm_bytes: int
    hbm_gbps: float  # bytes/s


# substring match against jax device_kind, first hit wins — keep more
# specific names ("v5 lite", "v5p") ahead of any shorter prefix they contain.
_SPECS: tuple[ChipSpec, ...] = (
    ChipSpec("v6 lite", 918e12, 1836e12, 32 << 30, 1640e9),  # Trillium / v6e
    ChipSpec("v5 lite", 197e12, 394e12, 16 << 30, 819e9),  # v5e
    ChipSpec("v5p", 459e12, 918e12, 95 << 30, 2765e9),
    ChipSpec("v4", 275e12, 275e12, 32 << 30, 1228e9),
    ChipSpec("v3", 61.4e12, 61.4e12, 16 << 30, 450e9),  # per core
    ChipSpec("v2", 23e12, 23e12, 8 << 30, 350e9),  # per core
)

# CPU fallback keeps MFU math runnable in CI; the number is meaningless and
# flagged by spec.kind so callers can label it.
_CPU = ChipSpec("cpu-fallback", 1e12, 1e12, 8 << 30, 50e9)


def chip_spec(device=None) -> ChipSpec:
    """Spec for a jax device (default: the first visible device).

    ``ATPU_PEAK_BF16_TFLOPS`` / ``ATPU_HBM_GBPS`` override for unlisted or
    derated parts.
    """
    if device is None:
        import jax

        devices = jax.devices()
        device = devices[0] if devices else None
    kind = str(getattr(device, "device_kind", "") or "").lower()
    spec = _CPU
    for s in _SPECS:
        if s.kind in kind:
            spec = s
            break
    flops_env = os.environ.get("ATPU_PEAK_BF16_TFLOPS")
    bw_env = os.environ.get("ATPU_HBM_GBPS")
    if flops_env or bw_env:
        spec = ChipSpec(
            spec.kind,
            float(flops_env) * 1e12 if flops_env else spec.bf16_flops,
            spec.int8_ops,
            spec.hbm_bytes,
            float(bw_env) * 1e9 if bw_env else spec.hbm_gbps,
        )
    return spec
