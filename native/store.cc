// Native store implementation — see store.h for the role and semantics spec.
#include "store.h"

#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdlib>

namespace atpu {

static double now_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

static const char* WRONGTYPE = "WRONGTYPE key holds another value type";

// Timed cv wait helper. Production waits on the steady clock
// (condition_variable::wait_for -> pthread_cond_clockwait: immune to
// wall-clock steps). gcc-10's libtsan does not intercept clockwait
// (gcc PR #98034), so under tsan an uninstrumented wait "leaks" the
// mutex into the lock-held set and every later access under that lock
// misreports as a race/double-lock — the sanitizer build waits on the
// system clock instead (pthread_cond_timedwait, intercepted).
template <class Rep, class Period, class Pred>
static bool cv_timed_wait(std::condition_variable& cv,
                          std::unique_lock<std::mutex>& lk,
                          std::chrono::duration<Rep, Period> d, Pred pred) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(lk, std::chrono::system_clock::now() + d, pred);
#else
  return cv.wait_for(lk, d, pred);
#endif
}

Store::Store(const std::string& aof_path) {
  if (!aof_path.empty()) {
    long valid = aof_load(aof_path);
    if (valid >= 0) {
      // Torn tail (crash mid-append): replay stopped at the last complete
      // record. TRUNCATE the file to that offset before reopening for
      // append — appending after torn bytes would strand every
      // post-recovery write behind an unparseable record, silently losing
      // them on the NEXT reopen.
      if (::truncate(aof_path.c_str(), valid) != 0) {
        // truncate failed (perms?): refuse to append after garbage
        std::fprintf(stderr, "[atpu-store] aof truncate to %ld failed: %d\n",
                     valid, errno);
        return;
      }
    }
    aof_ = std::fopen(aof_path.c_str(), "ab");
    if (aof_) sync_thread_ = std::thread(&Store::aof_sync_loop, this);
  }
}

Store::~Store() {
  if (sync_thread_.joinable()) {
    {
      std::lock_guard<std::mutex> lk(sync_mu_);
      sync_stop_ = true;
    }
    sync_cv_.notify_all();
    sync_thread_.join();
  }
  if (aof_) {
    std::fflush(aof_);
    ::fdatasync(::fileno(aof_));
    std::fclose(aof_);
  }
}

bool Store::live_locked(const std::string& key) {
  auto it = data_.find(key);
  if (it == data_.end()) return false;
  if (it->second.expire_at >= 0 && now_s() >= it->second.expire_at) {
    data_.erase(it);
    return false;
  }
  return true;
}

Value* Store::typed_locked(const std::string& key, Value::Type t, bool create,
                           std::string* err) {
  if (live_locked(key)) {
    Value& v = data_[key];
    if (v.type != t) {
      *err = WRONGTYPE;
      return nullptr;
    }
    return &v;
  }
  if (create) {
    Value& v = data_[key];
    v = Value();
    v.type = t;
    return &v;
  }
  return nullptr;
}

// Normalize Redis-style negative indices for LRANGE/LTRIM (inclusive stop).
static void norm_range(long long n, long long* start, long long* stop) {
  if (*start < 0) *start = std::max(0LL, n + *start);
  if (*stop < 0) *stop = n + *stop;
  if (*stop >= n) *stop = n - 1;
}

// Allowlist + key-namespace check for a single engine-originated op.
// Returns "" when permitted, else the rejection message.
static std::string ns_check(const Request& req, const std::string& ns) {
  static const std::set<uint8_t> allowed = {
      OP_SET, OP_GET, OP_DEL, OP_EXISTS, OP_KEYS, OP_EXPIRE, OP_TTL,
      OP_RPUSH, OP_LPUSH, OP_LREM, OP_LRANGE, OP_LLEN, OP_LTRIM,
      OP_HSET, OP_HINCRBY, OP_HGETALL, OP_PIPELINE};
  if (!allowed.count(req.op)) return "op not allowed for engines";
  if (req.op == OP_PIPELINE) return "";  // subs are checked individually
  if (req.args.empty()) return "key outside agent namespace";
  // every key arg must be namespaced: DEL takes keys in all positions,
  // everything else keys only in arg0 (remaining args are values/indices)
  size_t key_args = (req.op == OP_DEL) ? req.args.size() : 1;
  for (size_t i = 0; i < key_args; i++)
    if (req.args[i].rfind(ns, 0) != 0) return "key outside agent namespace";
  return "";
}

std::string Store::execute(const Request& req, const std::string& ns) {
  // Namespace + allowlist enforcement for engine (UDS) callers.
  if (!ns.empty()) {
    std::string err = ns_check(req, ns);
    if (!err.empty()) return resp_err(err);
    if (req.op == OP_PIPELINE) {
      // validate ALL subs (framing, nesting, allowlist, namespace) before
      // executing ANY, so a rejected batch never partially applies — parity
      // with the HTTP /internal/store endpoint's whole-batch 403
      std::vector<Request> subs(req.args.size());
      for (size_t i = 0; i < req.args.size(); i++) {
        const auto& sub_raw = req.args[i];
        if (!parse_request(reinterpret_cast<const uint8_t*>(sub_raw.data()),
                           sub_raw.size(), &subs[i]))
          return resp_err("malformed pipeline entry");
        if (subs[i].op == OP_PIPELINE) return resp_err("nested pipeline");
        err = ns_check(subs[i], ns);
        if (!err.empty()) return resp_err(err);
      }
      std::vector<std::string> outs;
      for (const auto& sub : subs)
        outs.push_back(execute(sub, ns));  // depth 1 (nested rejected above)
      return make_response(RESP_OK, outs);
    }
  }

  if (req.op == OP_PIPELINE) {
    std::vector<std::string> outs;
    for (const auto& sub_raw : req.args) {
      Request sub;
      if (!parse_request(reinterpret_cast<const uint8_t*>(sub_raw.data()),
                         sub_raw.size(), &sub))
        return resp_err("malformed pipeline entry");
      if (sub.op == OP_PIPELINE) return resp_err("nested pipeline");
      outs.push_back(execute(sub));
    }
    return make_response(RESP_OK, outs);
  }
  if (req.op == OP_PUBLISH) {
    if (req.args.size() != 2) return resp_err("PUBLISH needs channel message");
    return resp_int(publish(req.args[0], req.args[1]));
  }

  std::string aof_rec;
  std::string resp;
  {
    std::lock_guard<std::mutex> lk(mu_);
    resp = execute_locked(req, aof_ ? &aof_rec : nullptr);
    // append while holding mu_ so the AOF order matches apply order —
    // otherwise concurrent writers could log mutations out of order and
    // replay would reconstruct a state the live store never had
    if (!aof_rec.empty() && resp.size() && resp[0] == RESP_OK) aof_append(aof_rec);
  }
  return resp;
}

// Serialize a mutating request into an AOF record. SET rewrites to SETEXAT
// (absolute deadline) so replay after restart honors the original expiry.
static std::string aof_record(uint8_t op, const std::vector<std::string>& args) {
  std::string rec;
  rec.push_back(static_cast<char>(op));
  put_u32(rec, static_cast<uint32_t>(args.size()));
  for (const auto& a : args) put_arg(rec, a);
  std::string framed;
  put_u32(framed, static_cast<uint32_t>(rec.size()));
  framed += rec;
  return framed;
}

std::string Store::execute_locked(const Request& req, std::string* aof_out) {
  const auto& a = req.args;
  std::string err;
  auto wrongtype = [&]() { return resp_err(err); };

  switch (req.op) {
    case OP_SET:
    case OP_SETEXAT: {
      if (a.size() != 3) return resp_err("SET needs key value ttl");
      double expire_at = -1.0;
      if (!a[2].empty()) {
        double v = std::strtod(a[2].c_str(), nullptr);
        expire_at = (req.op == OP_SETEXAT) ? v : now_s() + v;
      }
      Value& val = data_[a[0]];
      val = Value();
      val.type = Value::STR;
      val.str = a[1];
      val.expire_at = expire_at;
      if (aof_out)
        *aof_out = aof_record(OP_SETEXAT,
                              {a[0], a[1], expire_at < 0 ? "" : std::to_string(expire_at)});
      return resp_ok();
    }
    case OP_GET: {
      if (a.size() != 1) return resp_err("GET needs key");
      if (!live_locked(a[0])) return resp_nil();
      Value& v = data_[a[0]];
      if (v.type != Value::STR) return resp_err(WRONGTYPE);
      return resp_ok1(v.str);
    }
    case OP_DEL: {
      long long n = 0;
      for (const auto& key : a) {
        if (live_locked(key)) n++;
        data_.erase(key);
      }
      if (aof_out && !a.empty()) *aof_out = aof_record(OP_DEL, a);
      return resp_int(n);
    }
    case OP_EXISTS: {
      if (a.size() != 1) return resp_err("EXISTS needs key");
      return resp_int(live_locked(a[0]) ? 1 : 0);
    }
    case OP_KEYS: {
      if (a.size() != 1) return resp_err("KEYS needs pattern");
      std::vector<std::string> out;
      std::vector<std::string> doomed;
      for (auto& kv : data_) {
        if (kv.second.expire_at >= 0 && now_s() >= kv.second.expire_at) {
          doomed.push_back(kv.first);
          continue;
        }
        if (glob_match(a[0], kv.first)) out.push_back(kv.first);
      }
      for (const auto& k : doomed) data_.erase(k);
      std::sort(out.begin(), out.end());
      return make_response(RESP_OK, out);
    }
    case OP_EXPIRE:
    case OP_EXPIREAT: {
      if (a.size() != 2) return resp_err("EXPIRE needs key ttl");
      if (!live_locked(a[0])) return resp_int(0);
      double arg = std::strtod(a[1].c_str(), nullptr);
      double deadline = (req.op == OP_EXPIREAT) ? arg : now_s() + arg;
      data_[a[0]].expire_at = deadline;
      // logged with the absolute deadline so replay honors the original expiry
      if (aof_out) *aof_out = aof_record(OP_EXPIREAT, {a[0], std::to_string(deadline)});
      return resp_int(1);
    }
    case OP_TTL: {
      if (a.size() != 1) return resp_err("TTL needs key");
      if (!live_locked(a[0])) return resp_nil();
      double exp = data_[a[0]].expire_at;
      if (exp < 0) return resp_nil();
      double rem = exp - now_s();
      return resp_ok1(std::to_string(rem < 0 ? 0.0 : rem));
    }
    case OP_SADD: {
      if (a.size() < 2) return resp_err("SADD needs key member...");
      Value* v = typed_locked(a[0], Value::SET, true, &err);
      if (!v) return wrongtype();
      size_t before = v->sset.size();
      for (size_t i = 1; i < a.size(); i++) v->sset.insert(a[i]);
      if (aof_out) *aof_out = aof_record(OP_SADD, a);
      return resp_int(static_cast<long long>(v->sset.size() - before));
    }
    case OP_SREM: {
      if (a.size() < 2) return resp_err("SREM needs key member...");
      Value* v = typed_locked(a[0], Value::SET, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return resp_int(0);
      long long n = 0;
      for (size_t i = 1; i < a.size(); i++) n += v->sset.erase(a[i]);
      if (v->sset.empty()) data_.erase(a[0]);
      if (aof_out) *aof_out = aof_record(OP_SREM, a);
      return resp_int(n);
    }
    case OP_SMEMBERS: {
      if (a.size() != 1) return resp_err("SMEMBERS needs key");
      Value* v = typed_locked(a[0], Value::SET, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return make_response(RESP_OK, {});
      return make_response(RESP_OK,
                           std::vector<std::string>(v->sset.begin(), v->sset.end()));
    }
    case OP_RPUSH:
    case OP_LPUSH: {
      if (a.size() < 2) return resp_err("PUSH needs key value...");
      Value* v = typed_locked(a[0], Value::LIST, true, &err);
      if (!v) return wrongtype();
      for (size_t i = 1; i < a.size(); i++) {
        if (req.op == OP_RPUSH)
          v->list.push_back(a[i]);
        else
          v->list.push_front(a[i]);
      }
      if (aof_out) *aof_out = aof_record(req.op, a);
      return resp_int(static_cast<long long>(v->list.size()));
    }
    case OP_LREM: {
      if (a.size() != 3) return resp_err("LREM needs key count value");
      Value* v = typed_locked(a[0], Value::LIST, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return resp_int(0);
      long long count = std::strtoll(a[1].c_str(), nullptr, 10);
      const std::string& target = a[2];
      long long removed = 0;
      std::deque<std::string> out;
      if (count >= 0) {
        long long limit = count > 0 ? count : static_cast<long long>(v->list.size());
        for (auto& item : v->list) {
          if (item == target && removed < limit)
            removed++;
          else
            out.push_back(std::move(item));
        }
      } else {
        long long limit = -count;
        for (auto it = v->list.rbegin(); it != v->list.rend(); ++it) {
          if (*it == target && removed < limit)
            removed++;
          else
            out.push_front(std::move(*it));
        }
      }
      v->list = std::move(out);
      if (v->list.empty()) data_.erase(a[0]);
      if (aof_out) *aof_out = aof_record(OP_LREM, a);
      return resp_int(removed);
    }
    case OP_LRANGE: {
      if (a.size() != 3) return resp_err("LRANGE needs key start stop");
      Value* v = typed_locked(a[0], Value::LIST, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return make_response(RESP_OK, {});
      long long n = static_cast<long long>(v->list.size());
      long long start = std::strtoll(a[1].c_str(), nullptr, 10);
      long long stop = std::strtoll(a[2].c_str(), nullptr, 10);
      norm_range(n, &start, &stop);
      std::vector<std::string> out;
      for (long long i = start; i <= stop && i < n; i++)
        if (i >= 0) out.push_back(v->list[i]);
      return make_response(RESP_OK, out);
    }
    case OP_LLEN: {
      if (a.size() != 1) return resp_err("LLEN needs key");
      Value* v = typed_locked(a[0], Value::LIST, false, &err);
      if (!err.empty()) return wrongtype();
      return resp_int(v ? static_cast<long long>(v->list.size()) : 0);
    }
    case OP_LTRIM: {
      if (a.size() != 3) return resp_err("LTRIM needs key start stop");
      Value* v = typed_locked(a[0], Value::LIST, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return resp_ok();
      long long n = static_cast<long long>(v->list.size());
      long long start = std::strtoll(a[1].c_str(), nullptr, 10);
      long long stop = std::strtoll(a[2].c_str(), nullptr, 10);
      norm_range(n, &start, &stop);
      std::deque<std::string> kept;
      for (long long i = start; i <= stop && i < n; i++)
        if (i >= 0) kept.push_back(std::move(v->list[i]));
      if (kept.empty())
        data_.erase(a[0]);
      else
        v->list = std::move(kept);
      if (aof_out) *aof_out = aof_record(OP_LTRIM, a);
      return resp_ok();
    }
    case OP_ZADD: {
      if (a.size() != 3) return resp_err("ZADD needs key score member");
      Value* v = typed_locked(a[0], Value::ZSET, true, &err);
      if (!v) return wrongtype();
      v->zscores[a[2]] = std::strtod(a[1].c_str(), nullptr);
      if (aof_out) *aof_out = aof_record(OP_ZADD, a);
      return resp_ok();
    }
    case OP_ZRANGEBYSCORE: {
      if (a.size() != 4) return resp_err("ZRANGEBYSCORE needs key min max limit");
      Value* v = typed_locked(a[0], Value::ZSET, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return make_response(RESP_OK, {});
      double lo = std::strtod(a[1].c_str(), nullptr);
      double hi = std::strtod(a[2].c_str(), nullptr);
      long long limit = a[3].empty() ? -1 : std::strtoll(a[3].c_str(), nullptr, 10);
      std::vector<std::pair<double, std::string>> hits;
      for (const auto& kv : v->zscores)
        if (kv.second >= lo && kv.second <= hi) hits.push_back({kv.second, kv.first});
      std::sort(hits.begin(), hits.end());
      std::vector<std::string> out;
      for (const auto& h : hits) {
        if (limit >= 0 && static_cast<long long>(out.size()) >= limit) break;
        out.push_back(h.second);
      }
      return make_response(RESP_OK, out);
    }
    case OP_ZREMRANGEBYSCORE: {
      if (a.size() != 3) return resp_err("ZREMRANGEBYSCORE needs key min max");
      Value* v = typed_locked(a[0], Value::ZSET, false, &err);
      if (!err.empty()) return wrongtype();
      if (!v) return resp_int(0);
      double lo = std::strtod(a[1].c_str(), nullptr);
      double hi = std::strtod(a[2].c_str(), nullptr);
      long long n = 0;
      for (auto it = v->zscores.begin(); it != v->zscores.end();) {
        if (it->second >= lo && it->second <= hi) {
          it = v->zscores.erase(it);
          n++;
        } else {
          ++it;
        }
      }
      if (v->zscores.empty()) data_.erase(a[0]);
      if (aof_out) *aof_out = aof_record(OP_ZREMRANGEBYSCORE, a);
      return resp_int(n);
    }
    case OP_ZCARD: {
      if (a.size() != 1) return resp_err("ZCARD needs key");
      Value* v = typed_locked(a[0], Value::ZSET, false, &err);
      if (!err.empty()) return wrongtype();
      return resp_int(v ? static_cast<long long>(v->zscores.size()) : 0);
    }
    case OP_HSET: {
      if (a.size() != 3) return resp_err("HSET needs key field value");
      Value* v = typed_locked(a[0], Value::HASH, true, &err);
      if (!v) return wrongtype();
      v->hash[a[1]] = a[2];
      if (aof_out) *aof_out = aof_record(OP_HSET, a);
      return resp_ok();
    }
    case OP_HINCRBY: {
      if (a.size() != 3) return resp_err("HINCRBY needs key field amount");
      Value* v = typed_locked(a[0], Value::HASH, true, &err);
      if (!v) return wrongtype();
      long long cur = 0;
      auto it = v->hash.find(a[1]);
      if (it != v->hash.end()) cur = std::strtoll(it->second.c_str(), nullptr, 10);
      cur += std::strtoll(a[2].c_str(), nullptr, 10);
      v->hash[a[1]] = std::to_string(cur);
      if (aof_out) *aof_out = aof_record(OP_HSET, {a[0], a[1], v->hash[a[1]]});
      return resp_int(cur);
    }
    case OP_HGETALL: {
      if (a.size() != 1) return resp_err("HGETALL needs key");
      Value* v = typed_locked(a[0], Value::HASH, false, &err);
      if (!err.empty()) return wrongtype();
      std::vector<std::string> out;
      if (v)
        for (const auto& kv : v->hash) {
          out.push_back(kv.first);
          out.push_back(kv.second);
        }
      return make_response(RESP_OK, out);
    }
    case OP_FLUSH: {
      data_.clear();
      if (aof_out) *aof_out = aof_record(OP_FLUSH, {});
      return resp_ok();
    }
    default:
      return resp_err("unknown op " + std::to_string(req.op));
  }
}

// ---- pub/sub ---------------------------------------------------------------

int Store::publish(const std::string& channel, const std::string& message) {
  int n = 0;
  {
    std::lock_guard<std::mutex> lk(sub_mu_);
    for (auto& kv : subs_) {
      auto& sub = *kv.second;
      if (sub.closed) continue;
      for (const auto& pat : sub.patterns) {
        if (glob_match(pat, channel)) {
          sub.queue.push_back({channel, message});
          n++;
          break;
        }
      }
    }
  }
  if (n) sub_cv_.notify_all();
  return n;
}

uint64_t Store::subscribe(const std::vector<std::string>& patterns) {
  std::lock_guard<std::mutex> lk(sub_mu_);
  uint64_t id = next_sub_id_++;
  auto sub = std::make_shared<Subscription>();
  sub->patterns = patterns;
  subs_[id] = sub;
  return id;
}

int Store::sub_poll(uint64_t sub_id, int timeout_ms, std::string* channel,
                    std::string* message) {
  std::unique_lock<std::mutex> lk(sub_mu_);
  auto it = subs_.find(sub_id);
  if (it == subs_.end() || it->second->closed) return -1;
  auto sub = it->second;
  if (sub->queue.empty() && timeout_ms > 0) {
    cv_timed_wait(sub_cv_, lk, std::chrono::milliseconds(timeout_ms), [&] {
      return sub->closed || !sub->queue.empty();
    });
  }
  if (sub->closed) return -1;
  if (sub->queue.empty()) return 0;
  *channel = std::move(sub->queue.front().first);
  *message = std::move(sub->queue.front().second);
  sub->queue.pop_front();
  return 1;
}

void Store::sub_close(uint64_t sub_id) {
  {
    std::lock_guard<std::mutex> lk(sub_mu_);
    auto it = subs_.find(sub_id);
    if (it == subs_.end()) return;
    it->second->closed = true;
    subs_.erase(it);
  }
  sub_cv_.notify_all();
}

// ---- AOF -------------------------------------------------------------------

void Store::aof_append(const std::string& rec) {
  std::lock_guard<std::mutex> lk(aof_mu_);
  if (!aof_) return;
  std::fwrite(rec.data(), 1, rec.size(), aof_);
  // Durability policy: every acked write reaches the kernel page cache
  // (fflush — survives a killed daemon); fdatasync runs off the write path
  // on the background sync thread about once a second (Redis
  // appendfsync-everysec envelope — survives power loss minus <=1s). stdio
  // buffering alone would lose acked journal entries on SIGKILL.
  std::fflush(aof_);
  aof_dirty_.store(true, std::memory_order_release);
}

void Store::aof_sync_loop() {
  std::unique_lock<std::mutex> lk(sync_mu_);
  while (!sync_stop_) {
    // steady clock via condition_variable wait_for: immune to wall-clock
    // steps (NTP), unlike a now_s()-based cadence
    cv_timed_wait(sync_cv_, lk, std::chrono::seconds(1), [this] { return sync_stop_; });
    if (sync_stop_) break;
    if (!aof_dirty_.exchange(false, std::memory_order_acq_rel)) continue;
    int fd = -1;
    {
      std::lock_guard<std::mutex> alk(aof_mu_);
      if (aof_) fd = ::fileno(aof_);
    }
    // sync outside aof_mu_ so writers never stall behind disk latency
    if (fd >= 0) ::fdatasync(fd);
  }
}

void Store::aof_flush() {
  std::lock_guard<std::mutex> lk(aof_mu_);
  if (aof_) std::fflush(aof_);
}

long Store::aof_load(const std::string& path) {
  std::FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return -1;
  std::string buf;
  char chunk[1 << 16];
  size_t n;
  while ((n = std::fread(chunk, 1, sizeof(chunk), f)) > 0) buf.append(chunk, n);
  std::fclose(f);
  size_t pos = 0;
  while (pos + 4 <= buf.size()) {
    uint32_t rec_len = get_u32(reinterpret_cast<const uint8_t*>(buf.data() + pos));
    if (pos + 4 + rec_len > buf.size()) break;  // truncated tail record: stop
    Request req;
    if (parse_request(reinterpret_cast<const uint8_t*>(buf.data() + pos + 4),
                      rec_len, &req)) {
      std::lock_guard<std::mutex> lk(mu_);
      execute_locked(req, nullptr);
    }
    pos += 4 + rec_len;
  }
  // bytes of the last COMPLETE record replayed: the constructor truncates
  // any torn tail to here so reopen-and-continue appends stay parseable
  return static_cast<long>(pos);
}

}  // namespace atpu
