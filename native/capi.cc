// C ABI for the native layer — consumed by agentainer_tpu/store/native.py
// (ctypes) and agentainer_tpu/runtime/dataplane.py. All buffers returned via
// out-params are heap-allocated with malloc and must be freed with atpu_free.
#include <cstdlib>
#include <cstring>
#include <string>

#include "common.h"
#include "dataplane.h"
#include "store.h"

using atpu::Request;
using atpu::Store;

namespace {

// Copy a std::string into a malloc'd buffer for the Python side.
uint8_t* to_heap(const std::string& s, size_t* len) {
  *len = s.size();
  uint8_t* p = static_cast<uint8_t*>(std::malloc(s.size() ? s.size() : 1));
  if (s.size()) std::memcpy(p, s.data(), s.size());
  return p;
}

}  // namespace

extern "C" {

void* atpu_store_new(const char* aof_path) {
  return new Store(aof_path ? aof_path : "");
}

void atpu_store_free(void* h) { delete static_cast<Store*>(h); }

void atpu_free(void* p) { std::free(p); }

// Execute one encoded command; returns 0 and fills *resp/*resp_len.
int atpu_cmd(void* h, const uint8_t* req_buf, size_t req_len, uint8_t** resp,
             size_t* resp_len) {
  Request req;
  std::string out;
  if (!atpu::parse_request(req_buf, req_len, &req)) {
    out = atpu::resp_err("malformed request");
  } else {
    out = static_cast<Store*>(h)->execute(req);
  }
  *resp = to_heap(out, resp_len);
  return 0;
}

uint64_t atpu_subscribe(void* h, const uint8_t* patterns_buf, size_t len) {
  // patterns_buf: [u32 count]([u32 len][bytes])*
  std::vector<std::string> patterns;
  if (len >= 4) {
    uint32_t count = atpu::get_u32(patterns_buf);
    size_t pos = 4;
    for (uint32_t i = 0; i < count && pos + 4 <= len; i++) {
      uint32_t plen = atpu::get_u32(patterns_buf + pos);
      pos += 4;
      if (pos + plen > len) break;
      patterns.emplace_back(reinterpret_cast<const char*>(patterns_buf + pos), plen);
      pos += plen;
    }
  }
  return static_cast<Store*>(h)->subscribe(patterns);
}

// Returns 1 (message: *resp = [u32 chan_len][chan][msg]), 0 (timeout),
// -1 (closed/unknown sub).
int atpu_sub_poll(void* h, uint64_t sub_id, int timeout_ms, uint8_t** resp,
                  size_t* resp_len) {
  std::string channel, message;
  int rc = static_cast<Store*>(h)->sub_poll(sub_id, timeout_ms, &channel, &message);
  if (rc == 1) {
    std::string out;
    atpu::put_arg(out, channel);
    out += message;
    *resp = to_heap(out, resp_len);
  } else {
    *resp = nullptr;
    *resp_len = 0;
  }
  return rc;
}

void atpu_sub_close(void* h, uint64_t sub_id) {
  static_cast<Store*>(h)->sub_close(sub_id);
}

int atpu_publish(void* h, const char* channel, const uint8_t* msg, size_t msg_len) {
  return static_cast<Store*>(h)->publish(
      channel, std::string(reinterpret_cast<const char*>(msg), msg_len));
}

void atpu_aof_flush(void* h) { static_cast<Store*>(h)->aof_flush(); }

// ---- data plane ------------------------------------------------------------

void* atpu_dp_start(void* store, const char* listen_host, int listen_port,
                    const char* backend_host, int backend_port,
                    const char* uds_path) {
  auto* dp = new atpu::DataPlane(static_cast<Store*>(store),
                                 listen_host ? listen_host : "", listen_port,
                                 backend_host ? backend_host : "127.0.0.1",
                                 backend_port, uds_path ? uds_path : "");
  if (!dp->start()) {
    delete dp;
    return nullptr;
  }
  return dp;
}

int atpu_dp_port(void* dp) { return static_cast<atpu::DataPlane*>(dp)->port(); }

void atpu_dp_stop(void* dp) {
  auto* p = static_cast<atpu::DataPlane*>(dp);
  p->stop();
  delete p;
}

void atpu_dp_route_set(void* dp, const char* agent_id, const char* host, int port,
                       const char* status, int persist) {
  static_cast<atpu::DataPlane*>(dp)->route_set(agent_id, host, port, status,
                                               persist != 0);
}

void atpu_dp_route_del(void* dp, const char* agent_id) {
  static_cast<atpu::DataPlane*>(dp)->route_del(agent_id);
}

// Drain per-agent request counters: fills requests, latency_sum, latency_max.
void atpu_dp_counters_drain(void* dp, const char* agent_id, uint64_t* requests,
                            double* latency_sum, double* latency_max) {
  static_cast<atpu::DataPlane*>(dp)->counters_drain(agent_id, requests, latency_sum,
                                                    latency_max);
}

}  // extern "C"
