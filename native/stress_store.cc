// Multi-threaded store/AOF stress harness — built under asan/tsan/ubsan
// (native/Makefile `asan` / `tsan` / `ubsan` targets, driven by the repo's
// `make analyze-native`). The torn-AOF bug (PR 5: appends landed after an
// unparseable tail, vanishing on the next reopen) was exactly the class a
// harness like this catches mechanically: concurrent mutators + flushes +
// crash/reopen cycles, with the sanitizer watching the memory model.
//
// Phases:
//   1. hammer: N writer threads (SET/GET/DEL/RPUSH/LRANGE/HINCRBY/EXPIRE),
//      a pub/sub echo pair, and a flusher thread, all on one Store.
//   2. recovery: write a known state with AOF on, drop the store, reopen,
//      verify every key replayed.
//   3. torn tail: append garbage to the AOF, reopen (truncation path),
//      write more, reopen AGAIN, verify the post-recovery writes survived.
//
// Exit 0 on success; any sanitizer report fails the build target.

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <vector>

#include "store.h"

using atpu::Request;
using atpu::Store;

namespace {

Request req(uint8_t op, std::vector<std::string> args) {
  Request r;
  r.op = op;
  r.args = std::move(args);
  return r;
}

bool ok(const std::string& resp) {
  return !resp.empty() && resp[0] == atpu::RESP_OK;
}

// first value of a single-value OK response ("" otherwise)
std::string val(const std::string& resp) {
  if (resp.size() < 5 || resp[0] != atpu::RESP_OK) return "";
  uint32_t count = atpu::get_u32(reinterpret_cast<const uint8_t*>(resp.data()) + 1);
  if (count < 1 || resp.size() < 9) return "";
  uint32_t len = atpu::get_u32(reinterpret_cast<const uint8_t*>(resp.data()) + 5);
  if (resp.size() < 9 + len) return "";
  return resp.substr(9, len);
}

std::atomic<int> failures{0};

void expect(bool cond, const char* what) {
  if (!cond) {
    std::fprintf(stderr, "[stress] FAIL: %s\n", what);
    failures.fetch_add(1);
  }
}

void hammer_phase(const std::string& aof) {
  // fresh AOF per run: asan/tsan/ubsan share the build dir, and replaying
  // the previous sanitizer's 16k-record log would make each leg slower
  // and its starting state nondeterministic
  std::remove(aof.c_str());
  Store store(aof);
  constexpr int kThreads = 8;
  constexpr int kOps = 2000;
  std::atomic<bool> stop{false};

  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back([&store, t] {
      const std::string me = "w" + std::to_string(t);
      for (int i = 0; i < kOps; i++) {
        const std::string key = "k:" + std::to_string(i % 37);
        switch (i % 7) {
          case 0:
            expect(ok(store.execute(req(atpu::OP_SET, {key, me + ":" + std::to_string(i), ""}))),
                   "concurrent SET");
            break;
          case 1:
            store.execute(req(atpu::OP_GET, {key}));
            break;
          case 2:
            store.execute(req(atpu::OP_RPUSH, {"l:" + me, std::to_string(i)}));
            break;
          case 3:
            store.execute(req(atpu::OP_LRANGE, {"l:" + me, "0", "-1"}));
            break;
          case 4:
            store.execute(req(atpu::OP_HINCRBY, {"h:shared", me, "1"}));
            break;
          case 5:
            store.execute(req(atpu::OP_EXPIRE, {key, "30"}));
            break;
          case 6:
            store.execute(req(atpu::OP_DEL, {key}));
            break;
        }
      }
    });
  }
  // pub/sub pair: subscriber polls while a publisher fans out
  threads.emplace_back([&store, &stop] {
    uint64_t sub = store.subscribe({"chan:*"});
    std::string ch, msg;
    while (!stop.load()) store.sub_poll(sub, 10, &ch, &msg);
    store.sub_close(sub);
  });
  threads.emplace_back([&store, &stop] {
    int i = 0;
    while (!stop.load())
      store.publish("chan:" + std::to_string(i++ % 4), "ping");
  });
  // flusher: races AOF flush against the writers' appends
  threads.emplace_back([&store, &stop] {
    while (!stop.load()) {
      store.aof_flush();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });

  for (int t = 0; t < kThreads; t++) threads[t].join();
  stop.store(true);
  for (size_t t = kThreads; t < threads.size(); t++) threads[t].join();

  // every writer's hash field must equal its op count for that branch
  std::string h = store.execute(req(atpu::OP_HGETALL, {"h:shared"}));
  expect(ok(h), "HGETALL after hammer");
}

void recovery_phase(const std::string& aof) {
  std::remove(aof.c_str());
  {
    Store store(aof);
    for (int i = 0; i < 100; i++)
      store.execute(req(atpu::OP_SET, {"r:" + std::to_string(i), std::to_string(i * i), ""}));
    store.execute(req(atpu::OP_RPUSH, {"r:list", "a", "b", "c"}));
    store.aof_flush();
  }  // dtor: final flush + close
  Store reopened(aof);
  for (int i = 0; i < 100; i += 17) {
    std::string got = val(reopened.execute(req(atpu::OP_GET, {"r:" + std::to_string(i)})));
    expect(got == std::to_string(i * i), "AOF replay restores SET values");
  }
  std::string llen = val(reopened.execute(req(atpu::OP_LLEN, {"r:list"})));
  expect(llen == "3", "AOF replay restores lists");
}

void torn_tail_phase(const std::string& aof) {
  std::remove(aof.c_str());
  {
    Store store(aof);
    store.execute(req(atpu::OP_SET, {"t:before", "survives", ""}));
    store.aof_flush();
  }
  {  // simulate a crash mid-append: garbage bytes after the last record
    std::FILE* f = std::fopen(aof.c_str(), "ab");
    expect(f != nullptr, "open AOF for tear");
    const char garbage[] = "\x40\x00\x00\x00partial-record-torn-mid-write";
    std::fwrite(garbage, 1, sizeof(garbage) - 1, f);
    std::fclose(f);
  }
  {
    Store recovered(aof);  // ctor truncates the torn tail before appending
    expect(val(recovered.execute(req(atpu::OP_GET, {"t:before"}))) == "survives",
           "pre-tear state replays");
    recovered.execute(req(atpu::OP_SET, {"t:after", "must-persist", ""}));
    recovered.aof_flush();
  }
  Store again(aof);  // the PR-5 bug: post-recovery appends vanished HERE
  expect(val(again.execute(req(atpu::OP_GET, {"t:before"}))) == "survives",
         "pre-tear state survives second reopen");
  expect(val(again.execute(req(atpu::OP_GET, {"t:after"}))) == "must-persist",
         "post-recovery writes survive the next reopen");
}

}  // namespace

int main(int argc, char** argv) {
  const std::string dir = (argc > 1) ? argv[1] : "/tmp";
  std::printf("[stress] hammer (8 writers x 2000 ops + pub/sub + flusher)...\n");
  hammer_phase(dir + "/atpu_stress_hammer.aof");
  std::printf("[stress] AOF recovery...\n");
  recovery_phase(dir + "/atpu_stress_recovery.aof");
  std::printf("[stress] torn-tail truncation...\n");
  torn_tail_phase(dir + "/atpu_stress_torn.aof");
  if (failures.load()) {
    std::fprintf(stderr, "[stress] %d failures\n", failures.load());
    return 1;
  }
  std::printf("[stress] all phases passed\n");
  return 0;
}
