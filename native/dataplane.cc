// Data-plane implementation. See dataplane.h for the architecture.
#include "dataplane.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <chrono>
#include <cstring>
#include <random>

namespace atpu {

static double now_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

static double mono_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---- small utils -----------------------------------------------------------

static std::string lower(std::string s) {
  std::transform(s.begin(), s.end(), s.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return s;
}

static bool is_hop_by_hop(const std::string& lname) {
  // parity with server/app.py _HOP_BY_HOP
  static const std::set<std::string> hop = {
      "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
      "te",         "trailers",   "transfer-encoding",  "upgrade",
      "host",       "content-length", "content-encoding"};
  return hop.count(lname) > 0;
}

static std::string uuid4() {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  uint64_t hi = rng(), lo = rng();
  unsigned char b[16];
  std::memcpy(b, &hi, 8);
  std::memcpy(b + 8, &lo, 8);
  b[6] = (b[6] & 0x0f) | 0x40;  // version 4
  b[8] = (b[8] & 0x3f) | 0x80;  // variant
  char out[37];
  std::snprintf(out, sizeof(out),
                "%02x%02x%02x%02x-%02x%02x-%02x%02x-%02x%02x-"
                "%02x%02x%02x%02x%02x%02x",
                b[0], b[1], b[2], b[3], b[4], b[5], b[6], b[7], b[8], b[9], b[10],
                b[11], b[12], b[13], b[14], b[15]);
  return std::string(out, 36);
}

// ---- buffered socket I/O + HTTP parsing ------------------------------------

struct HttpMsg {
  // request fields
  std::string method, target, version;
  // response fields
  int status = 0;
  // shared
  std::vector<std::pair<std::string, std::string>> headers;  // original case
  std::string body;
  bool keepalive = true;

  std::string header(const std::string& lname) const {
    for (const auto& kv : headers)
      if (lower(kv.first) == lname) return kv.second;
    return "";
  }
};

struct SockBuf {
  int fd;
  std::string buf;
  explicit SockBuf(int f) : fd(f) {}

  // Returns false on EOF/error before any progress could complete.
  bool fill() {
    char chunk[1 << 14];
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buf.append(chunk, static_cast<size_t>(n));
    return true;
  }

  bool read_exact(size_t n, std::string* out) {
    while (buf.size() < n)
      if (!fill()) return false;
    out->assign(buf.data(), n);
    buf.erase(0, n);
    return true;
  }

  // Read through the next CRLF; returns the line without CRLF.
  bool read_line(std::string* out) {
    size_t pos;
    while ((pos = buf.find("\r\n")) == std::string::npos) {
      if (buf.size() > (1 << 20)) return false;  // header flood guard
      if (!fill()) return false;
    }
    out->assign(buf.data(), pos);
    buf.erase(0, pos + 2);
    return true;
  }
};

static bool send_all(int fd, const char* data, size_t len) {
  while (len) {
    ssize_t n = ::send(fd, data, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

static bool send_all(int fd, const std::string& s) {
  return send_all(fd, s.data(), s.size());
}

// Parse one HTTP message from the socket. is_response selects status-line vs
// request-line. Handles Content-Length and chunked bodies. `eof_clean`
// reports EOF-before-first-byte, which on a reused upstream connection means
// a stale keepalive, not a crash. `response_to_head`: HEAD responses carry
// Content-Length but no body (RFC 9110 §6.4.1), so body reads must be skipped.
static bool read_http(SockBuf& sb, bool is_response, HttpMsg* msg,
                      bool* eof_clean = nullptr, bool response_to_head = false) {
  static const long long MAX_BODY = 1LL << 31;  // shared CL/chunked cap
  if (eof_clean) *eof_clean = false;
  std::string line;
  if (sb.buf.empty() && eof_clean) {
    if (!sb.fill()) {
      *eof_clean = true;
      return false;
    }
  }
  // interim 1xx responses precede the real one: parse-and-discard (bounded)
  for (int interim = 0; interim < 4; interim++) {
    if (!sb.read_line(&line)) return false;
    msg->headers.clear();
    msg->body.clear();
    if (is_response) {
      // HTTP/1.1 200 OK
      if (line.size() < 12 || line.compare(0, 5, "HTTP/") != 0) return false;
      msg->status = std::atoi(line.c_str() + 9);
      msg->version = line.substr(0, 8);
    } else {
      size_t sp1 = line.find(' ');
      size_t sp2 = line.rfind(' ');
      if (sp1 == std::string::npos || sp2 == sp1) return false;
      msg->method = line.substr(0, sp1);
      msg->target = line.substr(sp1 + 1, sp2 - sp1 - 1);
      msg->version = line.substr(sp2 + 1);
    }
    // headers
    for (;;) {
      if (!sb.read_line(&line)) return false;
      if (line.empty()) break;
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string name = line.substr(0, colon);
      size_t vstart = colon + 1;
      while (vstart < line.size() && line[vstart] == ' ') vstart++;
      msg->headers.emplace_back(name, line.substr(vstart));
    }
    if (is_response && msg->status >= 100 && msg->status < 200)
      continue;  // 1xx carries no body; the real response follows
    break;
  }
  if (is_response && msg->status >= 100 && msg->status < 200)
    return false;  // 1xx flood
  std::string conn = lower(msg->header("connection"));
  msg->keepalive = (msg->version == "HTTP/1.1") ? conn != "close" : conn == "keep-alive";
  // bodyless responses: HEAD answers, 204, 304 (RFC 9110 §6.4.1)
  if (is_response &&
      (response_to_head || msg->status == 204 || msg->status == 304))
    return true;
  std::string te = lower(msg->header("transfer-encoding"));
  if (!te.empty() && te != "identity") {
    // chunked body decode (requests and responses)
    for (;;) {
      if (!sb.read_line(&line)) return false;
      // strict hex chunk size: >=1 hex digit, then end or ';' (extensions)
      char* endp = nullptr;
      errno = 0;
      long long sz = std::strtoll(line.c_str(), &endp, 16);
      if (endp == line.c_str() || errno == ERANGE || sz < 0) return false;
      if (*endp != '\0' && *endp != ';' && *endp != ' ' && *endp != '\r')
        return false;
      if (sz == 0) {
        // trailers until blank line
        while (sb.read_line(&line) && !line.empty()) {
        }
        break;
      }
      if (sz > MAX_BODY ||
          static_cast<long long>(msg->body.size()) + sz > MAX_BODY)
        return false;
      std::string chunk;
      if (!sb.read_exact(static_cast<size_t>(sz), &chunk)) return false;
      msg->body += chunk;
      if (!sb.read_line(&line)) return false;  // trailing CRLF
    }
    return true;
  }
  std::string cl = msg->header("content-length");
  if (!cl.empty()) {
    long long n = std::strtoll(cl.c_str(), nullptr, 10);
    if (n < 0 || n > MAX_BODY) return false;
    if (n > 0 && !sb.read_exact(static_cast<size_t>(n), &msg->body)) return false;
  }
  return true;
}

static std::string status_reason(int code) {
  switch (code) {
    case 200: return "OK";
    case 202: return "Accepted";
    case 400: return "Bad Request";
    case 401: return "Unauthorized";
    case 404: return "Not Found";
    case 502: return "Bad Gateway";
    case 503: return "Service Unavailable";
    case 504: return "Gateway Timeout";
    default: return "Status";
  }
}

// `cl_override`: a HEAD response's Content-Length must advertise the size the
// corresponding GET would have (RFC 9110 §9.3.2) even though no body is sent;
// pass the upstream's Content-Length header value there, else "" stamps
// body.size().
static std::string build_response(int code,
                                  const std::vector<std::pair<std::string, std::string>>& headers,
                                  const std::string& body, bool keepalive,
                                  const std::string& cl_override = "") {
  std::string out = "HTTP/1.1 " + std::to_string(code) + " " + status_reason(code) + "\r\n";
  bool have_ct = false;
  for (const auto& kv : headers) {
    std::string l = lower(kv.first);
    if (is_hop_by_hop(l)) continue;
    if (l == "content-type") have_ct = true;
    out += kv.first + ": " + kv.second + "\r\n";
  }
  if (!have_ct) out += "Content-Type: application/json\r\n";
  out += "Content-Length: " +
         (cl_override.empty() ? std::to_string(body.size()) : cl_override) + "\r\n";
  out += keepalive ? "Connection: keep-alive\r\n" : "Connection: close\r\n";
  out += "\r\n";
  out += body;
  return out;
}

// {"success":..,"message":..,"data":..} envelope (server.go:50-54 parity).
static std::string envelope(bool success, const std::string& message,
                            const std::string& data_json) {
  std::string out = "{\"success\":";
  out += success ? "true" : "false";
  out += ",\"message\":";
  json_escape_to(out, message);
  out += ",\"data\":";
  out += data_json.empty() ? "null" : data_json;
  out += "}";
  return out;
}

// ---- journal records (requests.go:27-49 shape, journal.py field parity) ----

struct JEntry {
  std::string rid, agent_id, method, path;
  std::vector<std::pair<std::string, std::string>> headers;
  std::string body;
  double created_at = 0;
};

static std::string record_json(const JEntry& e, const std::string& status,
                               int retry_count, const std::string& error,
                               const std::string& response_json) {
  std::string out = "{\"id\":";
  json_escape_to(out, e.rid);
  out += ",\"agent_id\":";
  json_escape_to(out, e.agent_id);
  out += ",\"method\":";
  json_escape_to(out, e.method);
  out += ",\"path\":";
  json_escape_to(out, e.path);
  out += ",\"headers\":{";
  bool first = true;
  for (const auto& kv : e.headers) {
    if (!first) out += ",";
    first = false;
    json_escape_to(out, kv.first);
    out += ":";
    json_escape_to(out, kv.second);
  }
  out += "},\"body_b64\":\"" + (e.body.empty() ? "" : b64_encode(e.body));
  out += "\",\"status\":";
  json_escape_to(out, status);
  out += ",\"retry_count\":" + std::to_string(retry_count);
  out += ",\"max_retries\":3,\"response\":";
  out += response_json.empty() ? "null" : response_json;
  out += ",\"error\":";
  json_escape_to(out, error);
  char ts[64];
  std::snprintf(ts, sizeof(ts), ",\"created_at\":%.6f,\"updated_at\":%.6f}",
                e.created_at, now_s());
  out += ts;
  return out;
}

// ---- store helpers (direct, no wire round-trip needed in-process) ----------

static void store_set_at(Store* s, const std::string& key, const std::string& val,
                         double expire_at) {
  Request r;
  r.op = OP_SETEXAT;
  r.args = {key, val, expire_at < 0 ? "" : std::to_string(expire_at)};
  s->execute(r);
}

static void store_rpush(Store* s, const std::string& key, const std::string& val) {
  Request r;
  r.op = OP_RPUSH;
  r.args = {key, val};
  s->execute(r);
}

static void store_lrem1(Store* s, const std::string& key, const std::string& val) {
  Request r;
  r.op = OP_LREM;
  r.args = {key, "1", val};
  s->execute(r);
}

static std::string store_get(Store* s, const std::string& key, bool* found) {
  Request r;
  r.op = OP_GET;
  r.args = {key};
  std::string resp = s->execute(r);
  if (resp.empty() || resp[0] != RESP_OK) {
    *found = false;
    return "";
  }
  *found = true;
  // [status u8][count u32][len u32][bytes]
  if (resp.size() < 9) {
    *found = false;
    return "";
  }
  uint32_t len = get_u32(reinterpret_cast<const uint8_t*>(resp.data() + 5));
  return resp.substr(9, len);
}

static constexpr double REQUEST_TTL_S = 24 * 3600;  // requests.go:106

// ---- DataPlane -------------------------------------------------------------

DataPlane::DataPlane(Store* store, const std::string& listen_host, int listen_port,
                     const std::string& backend_host, int backend_port,
                     const std::string& uds_path)
    : store_(store),
      listen_host_(listen_host),
      listen_port_(listen_port),
      backend_host_(backend_host),
      backend_port_(backend_port),
      uds_path_(uds_path) {}

DataPlane::~DataPlane() { stop(); }

static int make_tcp_listener(const std::string& host, int port, int* actual_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // honor the configured bind host (the aiohttp fallback does) — a
  // loopback-only config must not expose the unauthenticated /agent/* path
  if (host.empty() || host == "0.0.0.0") {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 512) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  *actual_port = ntohs(addr.sin_port);
  return fd;
}

bool DataPlane::start() {
  listen_fd_ = make_tcp_listener(listen_host_, listen_port_, &port_);
  if (listen_fd_ < 0) return false;
  if (!uds_path_.empty()) {
    ::unlink(uds_path_.c_str());
    uds_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un ua{};
    ua.sun_family = AF_UNIX;
    std::strncpy(ua.sun_path, uds_path_.c_str(), sizeof(ua.sun_path) - 1);
    if (::bind(uds_fd_, reinterpret_cast<sockaddr*>(&ua), sizeof(ua)) < 0 ||
        ::listen(uds_fd_, 128) < 0) {
      ::close(uds_fd_);
      ::close(listen_fd_);
      return false;
    }
  }
  accept_thread_ = std::thread([this] { accept_loop(listen_fd_, false); });
  if (uds_fd_ >= 0)
    uds_thread_ = std::thread([this] { accept_loop(uds_fd_, true); });
  settle_thread_ = std::thread([this] { settle_loop(); });
  return true;
}

void DataPlane::settle_enqueue(std::function<void()> fn) {
  bool inline_run = false;
  {
    std::lock_guard<std::mutex> lk(settle_mu_);
    if (settle_stop_ || settle_q_.size() > 100000) {
      // stopping or badly backed up: apply inline (backpressure) rather
      // than drop — journal consistency over latency. The store I/O runs
      // OUTSIDE the lock so overload doesn't serialize every conn thread.
      inline_run = true;
    } else {
      settle_q_.push_back(std::move(fn));
    }
  }
  if (inline_run) {
    fn();
    return;
  }
  settle_cv_.notify_one();
}

void DataPlane::settle_loop() {
  std::unique_lock<std::mutex> lk(settle_mu_);
  for (;;) {
    settle_cv_.wait(lk, [this] { return settle_stop_ || !settle_q_.empty(); });
    while (!settle_q_.empty()) {
      auto fn = std::move(settle_q_.front());
      settle_q_.pop_front();
      lk.unlock();
      fn();
      lk.lock();
    }
    if (settle_stop_) return;
  }
}

void DataPlane::stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR), ::close(listen_fd_);
  if (uds_fd_ >= 0) ::shutdown(uds_fd_, SHUT_RDWR), ::close(uds_fd_);
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    for (int fd : conns_) ::shutdown(fd, SHUT_RDWR);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  if (uds_thread_.joinable()) uds_thread_.join();
  // wait for detached connection threads to leave store code — the owner
  // frees the store right after stop() returns. All their fds (client AND
  // upstream) were just shutdown(), so blocked recvs return immediately.
  for (int i = 0; i < 500 && active_conns_.load() > 0; i++)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  // conn threads can no longer enqueue (settle_enqueue under settle_mu_ runs
  // inline once settle_stop_ is set); drain what's queued, then join
  {
    std::lock_guard<std::mutex> lk(settle_mu_);
    settle_stop_ = true;
  }
  settle_cv_.notify_one();
  if (settle_thread_.joinable()) settle_thread_.join();
  if (!uds_path_.empty()) ::unlink(uds_path_.c_str());
}

void DataPlane::track(int fd, bool add) {
  std::lock_guard<std::mutex> lk(conn_mu_);
  if (add)
    conns_.insert(fd);
  else
    conns_.erase(fd);
}

void DataPlane::accept_loop(int fd, bool uds) {
  for (;;) {
    int conn = ::accept(fd, nullptr, nullptr);
    if (conn < 0) {
      if (stopping_) return;
      // EMFILE/EINTR etc.: back off instead of spinning the core
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    if (!uds) {
      int one = 1;
      ::setsockopt(conn, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    track(conn, true);
    std::thread t(uds ? &DataPlane::handle_uds_conn : &DataPlane::handle_conn, this,
                  conn);
    t.detach();
  }
}

void DataPlane::route_set(const std::string& agent_id, const std::string& host,
                          int port, const std::string& status, bool persist) {
  std::lock_guard<std::mutex> lk(route_mu_);
  routes_[agent_id] = Route{host, port, status, persist};
}

void DataPlane::route_del(const std::string& agent_id) {
  std::lock_guard<std::mutex> lk(route_mu_);
  routes_.erase(agent_id);
}

void DataPlane::counters_drain(const std::string& agent_id, uint64_t* requests,
                               double* latency_sum, double* latency_max) {
  std::lock_guard<std::mutex> lk(counter_mu_);
  auto it = counters_.find(agent_id);
  if (it == counters_.end()) {
    *requests = 0;
    *latency_sum = 0;
    *latency_max = 0;
    return;
  }
  *requests = it->second.requests;
  *latency_sum = it->second.lat_sum;
  *latency_max = it->second.lat_max;
  counters_.erase(it);
}

// Per-connection context: owns upstream keepalive sockets.
struct ConnCtx {
  DataPlane* dp;
  int client_fd;
  std::unordered_map<std::string, int> upstream;  // "host:port" -> fd
  std::unordered_map<std::string, std::string> upstream_buf;

  ~ConnCtx() {
    for (auto& kv : upstream) {
      dp->track(kv.second, false);
      ::close(kv.second);
    }
  }

  void drop(const std::string& key, int fd) {
    dp->track(fd, false);
    ::close(fd);
    upstream.erase(key);
    upstream_buf.erase(key);
  }

  int connect_to(const std::string& host, int port, bool* refused) {
    *refused = false;
    // upstream fds are tracked in dp->conns_ so stop() can shutdown() them —
    // otherwise a conn thread blocked in a 30s upstream recv outlives stop()
    // and touches the store after the owner frees it
    if (dp->stopping_.load()) return -1;
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    timeval tv{30, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      // only numeric hosts expected (localhost engines); try 127.0.0.1
      ::inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      *refused = (errno == ECONNREFUSED || errno == ENOENT || errno == EHOSTUNREACH);
      ::close(fd);
      return -1;
    }
    dp->track(fd, true);
    // close the race where stop() snapshots conns_ between our stopping_
    // check and track(): self-shutdown so the pending recv fails fast
    if (dp->stopping_.load()) ::shutdown(fd, SHUT_RDWR);
    return fd;
  }

  // Send req to host:port reusing a cached connection; one silent retry on a
  // stale keepalive socket. Outcomes: 0 ok, 1 connection-refused/engine-gone,
  // 2 other failure (timeout / protocol error). `head` marks a HEAD request,
  // whose response advertises Content-Length without sending a body.
  int roundtrip(const std::string& host, int port, const std::string& raw_req,
                HttpMsg* resp, bool head = false) {
    std::string key = host + ":" + std::to_string(port);
    for (int attempt = 0; attempt < 2; attempt++) {
      bool fresh = false;
      auto it = upstream.find(key);
      int fd;
      if (it == upstream.end()) {
        bool refused = false;
        fd = connect_to(host, port, &refused);
        if (fd < 0) return refused ? 1 : 2;
        upstream[key] = fd;
        upstream_buf[key].clear();
        fresh = true;
      } else {
        fd = it->second;
      }
      if (!send_all(fd, raw_req)) {
        drop(key, fd);
        if (fresh) return 1;  // engine accepted then died: treat as gone
        continue;             // stale keepalive: retry once with fresh conn
      }
      SockBuf sb(fd);
      sb.buf = std::move(upstream_buf[key]);
      bool eof_clean = false;
      if (!read_http(sb, true, resp, &eof_clean, head)) {
        drop(key, fd);
        if (dp->stopping_.load()) return 2;
        if (!fresh && eof_clean) continue;  // stale keepalive
        return fresh && eof_clean ? 1 : 2;
      }
      upstream_buf[key] = std::move(sb.buf);
      if (!resp->keepalive) drop(key, fd);
      return 0;
    }
    return 2;
  }
};

// Build the raw upstream request for an agent dispatch or backend forward.
static std::string build_upstream_request(
    const std::string& method, const std::string& target,
    const std::vector<std::pair<std::string, std::string>>& headers,
    const std::string& body, const std::string& host_hdr,
    const std::string& request_id, bool strip_auth) {
  std::string out = method + " " + target + " HTTP/1.1\r\n";
  out += "Host: " + host_hdr + "\r\n";
  for (const auto& kv : headers) {
    std::string l = lower(kv.first);
    if (is_hop_by_hop(l)) continue;
    if (strip_auth && l == "authorization") continue;
    if (l == "x-agentainer-request-id" || l == "x-agentainer-replay") continue;
    out += kv.first + ": " + kv.second + "\r\n";
  }
  if (!request_id.empty()) out += "X-Agentainer-Request-ID: " + request_id + "\r\n";
  out += "Content-Length: " + std::to_string(body.size()) + "\r\n";
  out += "Connection: keep-alive\r\n\r\n";
  out += body;
  return out;
}

void DataPlane::handle_conn(int fd) {
  active_conns_++;
  ConnCtx ctx{this, fd};
  SockBuf sb(fd);
  timeval tv{75, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

  for (;;) {
    HttpMsg req;
    if (!read_http(sb, false, &req)) break;

    bool keep = req.keepalive;
    std::string resp_raw;

    if (req.target.rfind("/agent/", 0) == 0) {
      // ---- native proxy path ------------------------------------------
      size_t id_start = 7;
      size_t id_end = req.target.find_first_of("/?", id_start);
      std::string agent_id = req.target.substr(
          id_start, id_end == std::string::npos ? std::string::npos : id_end - id_start);
      std::string path = "/";
      if (id_end != std::string::npos) {
        if (req.target[id_end] == '/') {
          path = req.target.substr(id_end);
        } else {
          path = "/" + req.target.substr(id_end);  // bare ?query
        }
      }

      Route route;
      bool have_route = false;
      {
        std::lock_guard<std::mutex> lk(route_mu_);
        auto it = routes_.find(agent_id);
        if (it != routes_.end()) {
          route = it->second;
          have_route = true;
        }
      }
      if (!have_route) {
        resp_raw = build_response(
            404, {}, envelope(false, "agent not found: " + agent_id, ""), keep);
        if (!send_all(fd, resp_raw) || !keep) break;
        continue;
      }

      if (route.port == 0 && route.status == "running") {
        // python-owned route (replica fleet): a RUNNING agent with no
        // single endpoint means the aiohttp proxy owns its dispatch —
        // replica choice, session affinity, bounded cross-replica retry,
        // AND the journaling. Fall through to the management forward
        // below with the request untouched instead of dispatching
        // natively to one endpoint (which is exactly the primary-only
        // blind spot the routing tier exists to fix).
      } else {

      // journal entry (before dispatch — the signature guarantee)
      JEntry e;
      e.agent_id = agent_id;
      e.method = req.method;
      e.path = path;
      e.body = req.body;
      e.created_at = now_s();
      for (const auto& kv : req.headers) {
        std::string l = lower(kv.first);
        if (is_hop_by_hop(l) || l == "x-agentainer-replay" ||
            l == "x-agentainer-request-id")
          continue;
        e.headers.push_back(kv);
      }
      std::string rec_key;
      double rec_deadline = e.created_at + REQUEST_TTL_S;
      if (route.persist) {
        e.rid = uuid4();
        rec_key = "agent:" + agent_id + ":requests:" + e.rid;
        store_set_at(store_, rec_key, record_json(e, "pending", 0, "", ""),
                     rec_deadline);
        store_rpush(store_, "agent:" + agent_id + ":requests:pending", e.rid);
      }

      if (route.status != "running") {
        if (route.persist) {
          resp_raw = build_response(
              202, {},
              envelope(true,
                       "Agent is not running. Request queued and will be "
                       "replayed when the agent is back.",
                       "{\"request_id\":" + json_escape(e.rid) +
                           ",\"status\":\"pending\"}"),
              keep);
        } else {
          resp_raw =
              build_response(503, {}, envelope(false, "agent is not running", ""), keep);
        }
        if (!send_all(fd, resp_raw) || !keep) break;
        continue;
      }

      // pending→processing BEFORE dispatch: the replay worker's 5 s tick
      // re-dispatches PENDING entries of a running agent, so an in-flight
      // generation longer than one tick would execute twice without this
      // marker (journal.py stale-reclaim returns it to pending if we die)
      if (route.persist)
        store_set_at(store_, rec_key, record_json(e, "processing", 0, "", ""),
                     rec_deadline);

      std::string upstream_req = build_upstream_request(
          req.method, path, e.headers, req.body,
          route.host + ":" + std::to_string(route.port), e.rid, /*strip_auth=*/true);
      HttpMsg up;
      double t0 = mono_s();
      int rc = ctx.roundtrip(route.host, route.port, upstream_req, &up,
                             req.method == "HEAD");
      double dt = mono_s() - t0;

      bool loading = rc == 0 && up.status == 503 &&
                     lower(up.header("x-agentainer-loading")) == "true";
      if (rc == 1 || loading) {
        // engine gone (or still loading): entry returns to pending for the
        // replay worker; no retry charged (server.go:597-606 heuristic)
        if (route.persist)
          store_set_at(store_, rec_key, record_json(e, "pending", 0, "", ""),
                       rec_deadline);
        resp_raw = build_response(
            502, {},
            envelope(false, "agent unreachable; request left pending for replay", ""),
            keep);
      } else if (rc == 2) {
        // timeout / protocol error: first retry charged (journal.mark_failed
        // semantics — dp-originated entries always carry retry_count 0 here)
        if (route.persist)
          store_set_at(store_, rec_key,
                       record_json(e, "pending", 1, "dispatch failed", ""),
                       rec_deadline);
        resp_raw = build_response(
            504, {}, envelope(false, "agent request failed; retry recorded", ""), keep);
      } else {
        if (route.persist) {
          // settle off-path: archive the response + move pending→completed
          // on the background thread. The client's response doesn't wait
          // for archive I/O; the at-most-ms window where a replay tick
          // could see a completed entry still pending is covered by engine
          // idempotency (request-id memoization).
          Store* store = store_;
          settle_enqueue([store, e, agent_id, rec_key, rec_deadline, up]() {
            std::string resp_json = "{\"status_code\":" +
                                    std::to_string(up.status) + ",\"headers\":{";
            bool first = true;
            for (const auto& kv : up.headers) {
              if (!first) resp_json += ",";
              first = false;
              json_escape_to(resp_json, kv.first);
              resp_json += ":";
              json_escape_to(resp_json, kv.second);
            }
            resp_json += "},\"body_b64\":\"" +
                         (up.body.empty() ? "" : b64_encode(up.body)) + "\"}";
            store_set_at(store, rec_key,
                         record_json(e, "completed", 0, "", resp_json),
                         rec_deadline);
            store_lrem1(store, "agent:" + agent_id + ":requests:pending", e.rid);
            store_rpush(store, "agent:" + agent_id + ":requests:completed", e.rid);
          });
        }
        {
          std::lock_guard<std::mutex> lk(counter_mu_);
          Counter& c = counters_[agent_id];
          c.requests++;
          c.lat_sum += dt;
          c.lat_max = std::max(c.lat_max, dt);
        }
        if (route.persist)
          // span continuity: the journal id rides back to the caller so a
          // response correlates with /agents/{id}/requests + engine logs
          up.headers.emplace_back("X-Agentainer-Request-ID", e.rid);
        resp_raw = build_response(
            up.status, up.headers, up.body, keep,
            req.method == "HEAD" ? up.header("content-length") : "");
      }
      if (!send_all(fd, resp_raw) || !keep) break;
      continue;
      }  // end native-dispatch branch (python-owned routes fall through)
    }

    // ---- management path: forward verbatim to the Python server ----------
    std::string fwd = build_upstream_request(
        req.method, req.target, req.headers, req.body,
        backend_host_ + ":" + std::to_string(backend_port_), "", /*strip_auth=*/false);

    // log-follow responses never end: relay bytes as they arrive instead of
    // buffering the (unbounded) body through roundtrip(). Dedicated upstream
    // connection; both sockets close when either side goes away.
    // Match the Python handler's semantics: follow present and not 0/false.
    bool follow_stream = false;
    if (req.target.find("/logs") != std::string::npos) {
      size_t fpos = req.target.find("follow=");
      if (fpos != std::string::npos) {
        std::string val = req.target.substr(fpos + 7);
        size_t amp = val.find('&');
        if (amp != std::string::npos) val = val.substr(0, amp);
        follow_stream = !val.empty() && val != "0" && lower(val) != "false";
      }
    }
    if (follow_stream) {
      bool refused = false;
      int ufd = ctx.connect_to(backend_host_, backend_port_, &refused);
      if (ufd < 0 || !send_all(ufd, fwd)) {
        if (ufd >= 0) {
          track(ufd, false);
          ::close(ufd);
        }
        resp_raw = build_response(
            502, {}, envelope(false, "management backend unavailable", ""), false);
        send_all(fd, resp_raw);
        break;
      }
      // follow streams idle between log lines: poll BOTH sockets so an
      // upstream line relays promptly AND a client disconnect during an
      // idle stream tears the relay down (no leaked thread/fds)
      char buf[1 << 14];
      for (;;) {
        pollfd fds[2];
        fds[0] = {ufd, POLLIN, 0};
        fds[1] = {fd, POLLIN | POLLRDHUP, 0};
        int pr = ::poll(fds, 2, 1000);
        if (pr < 0) break;
        if (pr == 0) {
          if (stopping_.load()) break;
          continue;
        }
        if (fds[1].revents) {
          // bytes from the client mid-stream or HUP: either way, done —
          // a follow response accepts no further requests on this conn
          break;
        }
        if (fds[0].revents) {
          ssize_t n = ::recv(ufd, buf, sizeof(buf), 0);
          if (n <= 0) break;
          if (!send_all(fd, buf, static_cast<size_t>(n))) break;
        }
      }
      track(ufd, false);
      ::close(ufd);
      break;  // stream consumed the connection
    }

    HttpMsg up;
    int rc = ctx.roundtrip(backend_host_, backend_port_, fwd, &up,
                           req.method == "HEAD");
    if (rc != 0) {
      resp_raw = build_response(
          502, {}, envelope(false, "management backend unavailable", ""), keep);
    } else {
      resp_raw = build_response(
          up.status, up.headers, up.body, keep,
          req.method == "HEAD" ? up.header("content-length") : "");
    }
    if (!send_all(fd, resp_raw)) break;
    if (!keep) break;
  }
  track(fd, false);
  ::close(fd);
  active_conns_--;
}

// ---- UDS store protocol: [u32 len][encoded request] per frame --------------

void DataPlane::handle_uds_conn(int fd) {
  active_conns_++;
  SockBuf sb(fd);
  std::string ns;  // set after AUTH
  for (;;) {
    std::string len_raw;
    if (!sb.read_exact(4, &len_raw)) break;
    uint32_t len = get_u32(reinterpret_cast<const uint8_t*>(len_raw.data()));
    if (len > (64u << 20)) break;
    std::string frame;
    if (!sb.read_exact(len, &frame)) break;
    Request req;
    std::string resp;
    if (!parse_request(reinterpret_cast<const uint8_t*>(frame.data()), frame.size(),
                       &req)) {
      resp = resp_err("malformed request");
    } else if (req.op == OP_AUTH) {
      if (req.args.size() != 2) {
        resp = resp_err("AUTH needs agent_id token");
      } else {
        bool found = false;
        std::string expected = store_get(store_, "internal:token:" + req.args[0], &found);
        if (!found || expected.empty() || expected != req.args[1]) {
          resp = resp_err("invalid engine credentials");
        } else {
          ns = "agent:" + req.args[0] + ":";
          resp = resp_ok();
        }
      }
    } else if (ns.empty()) {
      resp = resp_err("AUTH required");
    } else {
      resp = store_->execute(req, ns);
    }
    std::string framed;
    put_u32(framed, static_cast<uint32_t>(resp.size()));
    framed += resp;
    if (!send_all(fd, framed)) break;
  }
  track(fd, false);
  ::close(fd);
  active_conns_--;
}

}  // namespace atpu
