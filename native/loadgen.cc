// Native HTTP load generator for the proxy benchmark.
//
// The bench host is a 1-core VM: the Python asyncio load generators burned
// most of the core the C++ data plane needed, so the measured req/s was
// bounded by the GENERATOR, not the system under test. This is a minimal
// single-threaded poll() loop over N keep-alive connections issuing
// POST {path} with a fixed JSON body and parsing Content-Length responses —
// a few microseconds of CPU per request instead of Python's hundreds.
//
// Usage: loadgen HOST PORT PATH N_REQUESTS N_CONNS
// Prints one JSON line: {"n":..,"wall_s":..,"p50_ms":..,"p99_ms":..}

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Conn {
  int fd = -1;
  size_t sent = 0;         // bytes of the current request written
  std::string inbuf;       // response bytes accumulated
  size_t need = 0;         // body bytes still expected (0 = parsing headers)
  bool headers_done = false;
  Clock::time_point t0;
  bool in_flight = false;
};

int connect_nonblock(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    return -1;
  }
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  return fd;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 6) {
    fprintf(stderr, "usage: %s HOST PORT PATH N_REQUESTS N_CONNS\n", argv[0]);
    return 2;
  }
  const char* host = argv[1];
  int port = atoi(argv[2]);
  std::string path = argv[3];
  long total = atol(argv[4]);
  int n_conns = atoi(argv[5]);
  if (total <= 0 || n_conns <= 0) return 2;

  const std::string body = "{\"message\": \"bench\"}";
  char reqbuf[512];
  int reqlen = snprintf(reqbuf, sizeof(reqbuf),
                        "POST %s HTTP/1.1\r\nHost: %s\r\n"
                        "Content-Type: application/json\r\n"
                        "Content-Length: %zu\r\nConnection: keep-alive\r\n\r\n%s",
                        path.c_str(), host, body.size(), body.c_str());

  std::vector<Conn> conns(static_cast<size_t>(n_conns));
  for (auto& c : conns) {
    c.fd = connect_nonblock(host, port);
    if (c.fd < 0) {
      fprintf(stderr, "connect failed: %s\n", strerror(errno));
      return 1;
    }
  }

  std::vector<double> lat_ms;
  lat_ms.reserve(static_cast<size_t>(total));
  long started = 0, done = 0;
  std::vector<pollfd> pfds(conns.size());
  auto wall0 = Clock::now();
  auto last_progress = wall0;

  while (done < total) {
    // stall watchdog: a dropped response must not spin this loop until the
    // caller's subprocess timeout — fail fast so the bench can fall back
    if (std::chrono::duration<double>(Clock::now() - last_progress).count() > 30.0) {
      fprintf(stderr, "no progress for 30s (%ld/%ld done)\n", done, total);
      return 1;
    }
    for (size_t i = 0; i < conns.size(); ++i) {
      Conn& c = conns[i];
      if (!c.in_flight && started < total) {
        c.in_flight = true;
        c.sent = 0;
        c.inbuf.clear();
        c.need = 0;
        c.headers_done = false;
        c.t0 = Clock::now();
        ++started;
      }
      pfds[i].fd = c.fd;
      pfds[i].events = 0;
      if (c.in_flight) {
        if (c.sent < static_cast<size_t>(reqlen)) pfds[i].events |= POLLOUT;
        pfds[i].events |= POLLIN;
      }
    }
    int rc = poll(pfds.data(), pfds.size(), 5000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      fprintf(stderr, "poll: %s\n", strerror(errno));
      return 1;
    }
    for (size_t i = 0; i < conns.size(); ++i) {
      Conn& c = conns[i];
      if (!c.in_flight) continue;
      if ((pfds[i].revents & POLLOUT) && c.sent < static_cast<size_t>(reqlen)) {
        ssize_t n = write(c.fd, reqbuf + c.sent, static_cast<size_t>(reqlen) - c.sent);
        if (n > 0) c.sent += static_cast<size_t>(n);
        else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          fprintf(stderr, "write: %s\n", strerror(errno));
          return 1;
        }
      }
      if (pfds[i].revents & (POLLIN | POLLERR | POLLHUP)) {
        char buf[8192];
        ssize_t n = read(c.fd, buf, sizeof(buf));
        if (n == 0) {
          // server closed the keep-alive (idle timeout / graceful restart):
          // reconnect this connection and resend the in-flight request
          close(c.fd);
          c.fd = connect_nonblock(host, port);
          if (c.fd < 0) {
            fprintf(stderr, "reconnect failed: %s\n", strerror(errno));
            return 1;
          }
          c.sent = 0;
          c.inbuf.clear();
          c.headers_done = false;
          c.need = 0;
          continue;
        }
        if (n < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) continue;
          fprintf(stderr, "read: %s\n", strerror(errno));
          return 1;
        }
        c.inbuf.append(buf, static_cast<size_t>(n));
        if (!c.headers_done) {
          size_t hdr_end = c.inbuf.find("\r\n\r\n");
          if (hdr_end == std::string::npos) continue;
          if (c.inbuf.compare(0, 12, "HTTP/1.1 200") != 0) {
            fprintf(stderr, "bad status: %.64s\n", c.inbuf.c_str());
            return 1;
          }
          size_t cl = 0;
          bool cl_found = false;
          // case-insensitive Content-Length scan within the header block
          for (size_t p = 0; p + 16 < hdr_end;) {
            size_t eol = c.inbuf.find("\r\n", p);
            if (eol == std::string::npos || eol > hdr_end) break;
            if (strncasecmp(c.inbuf.c_str() + p, "content-length:", 15) == 0) {
              cl = strtoul(c.inbuf.c_str() + p + 15, nullptr, 10);
              cl_found = true;
            }
            p = eol + 2;
          }
          if (!cl_found) {
            // chunked/close-delimited bodies would desync the keep-alive
            // stream — refuse loudly instead of corrupting every later
            // sample on this connection
            fprintf(stderr, "response without Content-Length (unsupported)\n");
            return 1;
          }
          c.headers_done = true;
          size_t have = c.inbuf.size() - (hdr_end + 4);
          c.need = (cl > have) ? cl - have : 0;
        } else {
          size_t got = static_cast<size_t>(n);
          c.need = (c.need > got) ? c.need - got : 0;
        }
        if (c.headers_done && c.need == 0) {
          double ms = std::chrono::duration<double, std::milli>(Clock::now() - c.t0).count();
          lat_ms.push_back(ms);
          c.in_flight = false;
          ++done;
          last_progress = Clock::now();
        }
      }
    }
  }

  double wall = std::chrono::duration<double>(Clock::now() - wall0).count();
  std::sort(lat_ms.begin(), lat_ms.end());
  auto pct = [&](double p) {
    size_t idx = static_cast<size_t>(p * (lat_ms.size() - 1));
    return lat_ms.empty() ? 0.0 : lat_ms[idx];
  };
  printf("{\"n\": %ld, \"wall_s\": %.4f, \"p50_ms\": %.3f, \"p99_ms\": %.3f}\n",
         done, wall, pct(0.5), pct(0.99));
  for (auto& c : conns) close(c.fd);
  return 0;
}
