// Native data plane — the hot request path of the control plane, in C++.
//
// In the reference, every proxied agent request flows through the Go server's
// proxy handler + Redis journal (internal/api/server.go:493-615,
// internal/requests/requests.go:64-275). Here that hot path runs on native
// threads with zero Python involvement:
//
//   client ──HTTP──▶ DataPlane ──journal──▶ Store (C++, in-process)
//                        │
//                        ├─ /agent/{id}/** : journal → forward to engine →
//                        │                   settle (completed/pending/failed)
//                        ├─ /internal/store via UDS: engine state ops,
//                        │                   token-authed, namespaced
//                        └─ everything else: forwarded to the Python
//                                            management server (aiohttp)
//
// The Python side owns policy (lifecycle, scheduling, replay, health) and
// updates the routing table; the C++ side owns per-request mechanics.
// Outcome classification parity: success → archive response; connection
// refused / engine vanished → journal entry stays pending for the replay
// worker (crash heuristic, server.go:597-606); timeout/protocol error →
// retry accounting toward dead-letter.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>

#include "store.h"

namespace atpu {

class DataPlane {
 public:
  DataPlane(Store* store, const std::string& listen_host, int listen_port,
            const std::string& backend_host, int backend_port,
            const std::string& uds_path);
  ~DataPlane();

  bool start();
  void stop();
  int port() const { return port_; }

  void route_set(const std::string& agent_id, const std::string& host, int port,
                 const std::string& status, bool persist);
  void route_del(const std::string& agent_id);

  void counters_drain(const std::string& agent_id, uint64_t* requests,
                      double* latency_sum, double* latency_max);

 private:
  struct Route {
    std::string host;
    int port = 0;
    std::string status;
    bool persist = true;
  };
  struct Counter {
    uint64_t requests = 0;
    double lat_sum = 0;
    double lat_max = 0;
  };

  void accept_loop(int fd, bool uds);
  void handle_conn(int fd);
  void handle_uds_conn(int fd);
  void track(int fd, bool add);

  // Deferred journal settles: the journal-BEFORE-dispatch write is the crash
  // guarantee and stays on the request path; the completed-state transition
  // is bookkeeping and runs on one background thread so its store ops and
  // JSON serialization never add to request latency.
  void settle_enqueue(std::function<void()> fn);
  void settle_loop();

  Store* store_;
  std::string listen_host_;
  int listen_port_;
  int port_ = 0;
  std::string backend_host_;
  int backend_port_;
  std::string uds_path_;

  int listen_fd_ = -1;
  int uds_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::atomic<int> active_conns_{0};
  std::thread accept_thread_;
  std::thread uds_thread_;

  std::mutex route_mu_;
  std::unordered_map<std::string, Route> routes_;

  std::mutex counter_mu_;
  std::unordered_map<std::string, Counter> counters_;

  std::mutex conn_mu_;
  std::set<int> conns_;

  std::thread settle_thread_;
  std::mutex settle_mu_;
  std::condition_variable settle_cv_;
  std::deque<std::function<void()>> settle_q_;
  bool settle_stop_ = false;

  friend struct ConnCtx;
};

}  // namespace atpu
