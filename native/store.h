// Native control-plane state store — the Redis role in the reference
// (internal/storage/storage.go + the key schema in SURVEY.md §2.2),
// implemented in C++ so the data plane journals requests without touching
// the Python interpreter, and so state survives daemon restarts via an AOF
// (the durability Redis gave the reference's Go server).
//
// Semantics mirror agentainer_tpu/store/memory.py (the behavioral spec both
// implementations are tested against): lazy TTL expiry, counted LREM,
// inclusive LRANGE/LTRIM stops, (score, member)-ordered ZRANGEBYSCORE,
// glob-pattern pub/sub.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <thread>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace atpu {

struct Value {
  enum Type { STR, LIST, SET, ZSET, HASH } type = STR;
  std::string str;
  std::deque<std::string> list;
  std::set<std::string> sset;
  std::map<std::string, double> zscores;          // member -> score
  std::map<std::string, std::string> hash;        // field -> value
  double expire_at = -1.0;                        // epoch seconds; -1 = none
};

struct Subscription {
  std::vector<std::string> patterns;
  std::deque<std::pair<std::string, std::string>> queue;  // (channel, message)
  bool closed = false;
};

class Store {
 public:
  explicit Store(const std::string& aof_path = "");
  ~Store();

  // Execute one encoded command (see common.h wire format). When `ns` is
  // non-empty, key/pattern args must start with it (engine UDS namespacing)
  // and ops outside the engine allowlist are rejected.
  std::string execute(const Request& req, const std::string& ns = "");

  // Pub/sub used in-process.
  int publish(const std::string& channel, const std::string& message);
  uint64_t subscribe(const std::vector<std::string>& patterns);
  // Returns 1 and fills channel/message, or 0 on timeout, -1 if closed/unknown.
  int sub_poll(uint64_t sub_id, int timeout_ms, std::string* channel, std::string* message);
  void sub_close(uint64_t sub_id);

  void aof_flush();

 private:
  bool live_locked(const std::string& key);  // expiry check; may erase
  Value* typed_locked(const std::string& key, Value::Type t, bool create, std::string* err);
  std::string execute_locked(const Request& req, std::string* aof_out);
  void aof_append(const std::string& rec);
  // replays the AOF; returns the byte offset of the last complete record
  // (the valid length a torn tail is truncated to), -1 if no file
  long aof_load(const std::string& path);

  std::mutex mu_;
  std::unordered_map<std::string, Value> data_;

  std::mutex sub_mu_;
  std::condition_variable sub_cv_;
  std::unordered_map<uint64_t, std::shared_ptr<Subscription>> subs_;
  uint64_t next_sub_id_ = 1;

  std::mutex aof_mu_;
  std::FILE* aof_ = nullptr;
  // everysec fdatasync runs on its own thread (see aof_sync_loop)
  void aof_sync_loop();
  std::atomic<bool> aof_dirty_{false};
  std::thread sync_thread_;
  std::mutex sync_mu_;
  std::condition_variable sync_cv_;
  bool sync_stop_ = false;
};

}  // namespace atpu
