// Shared helpers for the native layer: wire encoding, glob matching,
// base64, JSON string escaping, monotonic/epoch clocks.
//
// The wire format is the single command encoding used by (1) the in-process
// ctypes API, (2) the engine UDS store protocol, and (3) the data plane's
// internal journal calls — one dispatcher serves all three.
//
//   request:  [u8 opcode][u32 argc]([u32 len][bytes])*
//   response: [u8 status: 0 ok, 1 err, 2 nil][u32 count]([u32 len][bytes])*
//
// Integers/doubles travel as ASCII strings; values are binary-safe.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace atpu {

// ---- wire encoding ---------------------------------------------------------

inline void put_u32(std::string& out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);  // little-endian hosts only (x86/ARM TPU-VMs)
  out.append(b, 4);
}

inline uint32_t get_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline void put_arg(std::string& out, const std::string& s) {
  put_u32(out, static_cast<uint32_t>(s.size()));
  out.append(s);
}

struct Request {
  uint8_t op = 0;
  std::vector<std::string> args;
};

// Parse a request buffer; returns false on malformed input.
inline bool parse_request(const uint8_t* buf, size_t len, Request* out) {
  if (len < 5) return false;
  out->op = buf[0];
  uint32_t argc = get_u32(buf + 1);
  size_t pos = 5;
  out->args.clear();
  out->args.reserve(argc);
  for (uint32_t i = 0; i < argc; i++) {
    if (pos + 4 > len) return false;
    uint32_t alen = get_u32(buf + pos);
    pos += 4;
    if (pos + alen > len) return false;
    out->args.emplace_back(reinterpret_cast<const char*>(buf + pos), alen);
    pos += alen;
  }
  return pos == len;
}

enum RespStatus : uint8_t { RESP_OK = 0, RESP_ERR = 1, RESP_NIL = 2 };

// Opcodes — mirrored in agentainer_tpu/store/native.py (OP_*) and the engine
// store client. Keep numbering stable; it is the UDS wire protocol.
enum Op : uint8_t {
  OP_SET = 1,     // key value ttl("" = none, seconds otherwise)
  OP_GET = 2,     // key -> nil | [value]
  OP_DEL = 3,     // key... -> [n]
  OP_EXISTS = 4,  // key -> [0|1]
  OP_KEYS = 5,    // pattern -> [key...]
  OP_EXPIRE = 6,  // key ttl -> [0|1]
  OP_TTL = 7,     // key -> nil | [seconds]
  OP_SADD = 8,    // key member... -> [added]
  OP_SREM = 9,    // key member... -> [removed]
  OP_SMEMBERS = 10,
  OP_RPUSH = 11,  // key value... -> [len]
  OP_LPUSH = 12,
  OP_LREM = 13,   // key count value -> [removed]
  OP_LRANGE = 14, // key start stop -> [value...]
  OP_LLEN = 15,
  OP_LTRIM = 16,  // key start stop
  OP_ZADD = 17,   // key score member
  OP_ZRANGEBYSCORE = 18,  // key min max limit("" = none) -> [member...]
  OP_ZREMRANGEBYSCORE = 19,
  OP_ZCARD = 20,
  OP_HSET = 21,     // key field value
  OP_HINCRBY = 22,  // key field amount -> [n]
  OP_HGETALL = 23,  // key -> [f1 v1 f2 v2 ...]
  OP_PUBLISH = 24,  // channel message -> [receivers]
  OP_FLUSH = 25,
  OP_PIPELINE = 26,  // args are length-prefixed encoded sub-requests;
                     // response args are encoded sub-responses
  OP_AUTH = 27,      // agent_id token (UDS only)
  OP_SETEXAT = 28,   // key value expire_at_epoch("" = none) — AOF replay form
  OP_EXPIREAT = 29,  // key expire_at_epoch — AOF replay form of EXPIRE
};

inline std::string make_response(RespStatus st, const std::vector<std::string>& vals) {
  std::string out;
  out.push_back(static_cast<char>(st));
  put_u32(out, static_cast<uint32_t>(vals.size()));
  for (const auto& v : vals) put_arg(out, v);
  return out;
}

inline std::string resp_ok() { return make_response(RESP_OK, {}); }
inline std::string resp_ok1(const std::string& v) { return make_response(RESP_OK, {v}); }
inline std::string resp_nil() { return make_response(RESP_NIL, {}); }
inline std::string resp_err(const std::string& msg) { return make_response(RESP_ERR, {msg}); }
inline std::string resp_int(long long v) { return resp_ok1(std::to_string(v)); }

// ---- glob matching (fnmatch-style: * ? and literal) ------------------------

inline bool glob_match(const char* pat, const char* str) {
  // iterative star backtracking
  const char* star = nullptr;
  const char* ss = nullptr;
  while (*str) {
    if (*pat == '?' || *pat == *str) {
      pat++;
      str++;
    } else if (*pat == '*') {
      star = pat++;
      ss = str;
    } else if (star) {
      pat = star + 1;
      str = ++ss;
    } else {
      return false;
    }
  }
  while (*pat == '*') pat++;
  return *pat == '\0';
}

inline bool glob_match(const std::string& pat, const std::string& str) {
  return glob_match(pat.c_str(), str.c_str());
}

// ---- base64 ----------------------------------------------------------------

inline std::string b64_encode(const std::string& in) {
  static const char tbl[] = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve(((in.size() + 2) / 3) * 4);
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t n = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8 | (uint8_t)in[i + 2];
    out.push_back(tbl[(n >> 18) & 63]);
    out.push_back(tbl[(n >> 12) & 63]);
    out.push_back(tbl[(n >> 6) & 63]);
    out.push_back(tbl[n & 63]);
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t n = (uint8_t)in[i] << 16;
    out.push_back(tbl[(n >> 18) & 63]);
    out.push_back(tbl[(n >> 12) & 63]);
    out.append("==");
  } else if (i + 2 == in.size()) {
    uint32_t n = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8;
    out.push_back(tbl[(n >> 18) & 63]);
    out.push_back(tbl[(n >> 12) & 63]);
    out.push_back(tbl[(n >> 6) & 63]);
    out.push_back('=');
  }
  return out;
}

// ---- JSON string escaping (for journal records the Python side json.loads) -

inline void json_escape_to(std::string& out, const std::string& s) {
  out.push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': out.append("\\\""); break;
      case '\\': out.append("\\\\"); break;
      case '\n': out.append("\\n"); break;
      case '\r': out.append("\\r"); break;
      case '\t': out.append("\\t"); break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out.append(buf);
        } else {
          out.push_back(static_cast<char>(c));
        }
    }
  }
  out.push_back('"');
}

inline std::string json_escape(const std::string& s) {
  std::string out;
  json_escape_to(out, s);
  return out;
}

}  // namespace atpu
