#!/usr/bin/env bash
# Start the control-plane daemon in the background with a durable store.
# (Reference role: scripts/start-server.sh:1-52, which boots the server
# container + Redis sidecar; here the store is embedded so one process
# suffices.)
set -euo pipefail

ATPU_DATA_DIR="${ATPU_DATA_DIR:-$HOME/.agentainer}"
ATPU_SERVER_PORT="${ATPU_SERVER_PORT:-8081}"
ATPU_STORE_URL="${ATPU_STORE_URL:-native://$ATPU_DATA_DIR/store.aof}"
PIDFILE="$ATPU_DATA_DIR/agentainer.pid"
LOGFILE="$ATPU_DATA_DIR/daemon.log"

mkdir -p "$ATPU_DATA_DIR"

if [[ -f "$PIDFILE" ]] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    echo "already running (pid $(cat "$PIDFILE"))"
    exit 0
fi

# build the native store/data plane if it isn't there yet
if [[ ! -f "$(dirname "$0")/../native/build/libagentainer_native.so" ]]; then
    echo "building native components..."
    make -C "$(dirname "$0")/../native" >/dev/null
fi

export ATPU_DATA_DIR ATPU_SERVER_PORT ATPU_STORE_URL
nohup python -m agentainer_tpu.cli server --port "$ATPU_SERVER_PORT" \
    >> "$LOGFILE" 2>&1 &
echo $! > "$PIDFILE"

for _ in $(seq 1 50); do
    if curl -fsS "http://127.0.0.1:$ATPU_SERVER_PORT/health" >/dev/null 2>&1; then
        echo "agentainer server up on :$ATPU_SERVER_PORT (pid $(cat "$PIDFILE"), data in $ATPU_DATA_DIR)"
        exit 0
    fi
    sleep 0.2
done
echo "server did not become healthy; see $LOGFILE" >&2
exit 1
