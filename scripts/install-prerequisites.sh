#!/usr/bin/env bash
# Verify the environment and build the native components.
# (Reference role: scripts/install-prerequisites.sh — Docker/Redis checks
# become Python/JAX/toolchain checks here; nothing is installed, only
# verified, because TPU-VM images bake the deps.)
set -uo pipefail

ok=0; fail=0
check() {
    if eval "$2" >/dev/null 2>&1; then
        echo "  ok: $1"; ok=$((ok+1))
    else
        echo "  MISSING: $1   ($3)"; fail=$((fail+1))
    fi
}

echo "python environment:"
check "python >= 3.10"        "python -c 'import sys; assert sys.version_info >= (3,10)'" "install python3.10+"
check "jax"                   "python -c 'import jax'"            "pip install jax"
check "aiohttp"               "python -c 'import aiohttp'"        "pip install aiohttp"
check "numpy"                 "python -c 'import numpy'"          "pip install numpy"
check "optax (training)"      "python -c 'import optax'"          "pip install optax"
check "orbax (checkpoints)"   "python -c 'import orbax.checkpoint'" "pip install orbax-checkpoint"
check "safetensors (HF import)" "python -c 'import safetensors'"  "pip install safetensors"
check "pytest (tests)"        "python -c 'import pytest'"         "pip install pytest"

echo "native toolchain:"
check "g++"                   "command -v g++"                    "apt install g++"
check "make"                  "command -v make"                   "apt install make"

echo "accelerator:"
timeout 20 python - <<'PY' 2>/dev/null || echo "  note: no TPU visible or probe timed out (CPU fallback works for control plane + tests)"
import jax
ds = jax.devices()
print(f"  ok: {len(ds)} {ds[0].platform} device(s)")
PY

if [[ $fail -eq 0 ]]; then
    echo "building native store + data plane..."
    if make -C "$(dirname "$0")/../native" >/dev/null; then
        echo "  ok: native/build/libagentainer_native.so"
    else
        echo "  MISSING: native build failed (control plane falls back to the in-memory store; pass ATPU_STORE_URL=mem:// to acknowledge)"
    fi
fi
echo "$ok checks passed, $fail missing"
exit $((fail > 0))
