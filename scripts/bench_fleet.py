"""Fleet bench: goodput + p99 TTFT vs replica count, failover MTTR, and
resumed-stream token identity — BENCH_fleet.json.

Three tiers against a live daemon (TestClient; same harness as the chaos
soak):

1. **scaling sweep** — a tiny-LLM agent at replicas 1 / 2 / 4 takes a
   closed-loop concurrent burst of short chat turns; goodput (200s/s)
   and p50/p99 request latency (the TTFT proxy for a non-streaming
   engine) are recorded per replica count. The LLM engine is the honest
   scaling subject: decode is compute-bound, so independent replica
   PROCESSES parallelize across host cores, whereas the echo engine is
   proxy-bound and would only measure routing overhead. replicas=1 is
   the A/B baseline: it routes through the exact pre-fleet
   single-endpoint path (the router never engages).
2. **failover MTTR** — a 2-replica echo fleet under steady probes has one
   replica SIGKILLed; MTTR is the longest service gap observed at the
   caller (the fleet answer: a survivor serves while repair respawns).
3. **resumed-stream token identity** — a 2-replica tiny-LLM fleet runs
   the chaos soak's control/victim pair: the victim's replica dies
   MID-DECODE and the journaled turn must settle token-identical to the
   control on the surviving replica.

ATPU_FLEET_SMOKE=1 shortens the burst volumes (make fleet). Seeded:
traffic, routing p2c, and Retry-After jitter all derive from
ATPU_FLEET_SEED (default 1337).

Usage: JAX_PLATFORMS=cpu python scripts/bench_fleet.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from _benchlib import percentile, write_artifact  # noqa: E402

from agentainer_tpu.config import Config  # noqa: E402
from agentainer_tpu.daemon import (  # noqa: E402
    build_services,
    start_background,
    stop_background,
)
from agentainer_tpu.runtime.local import LocalBackend  # noqa: E402
from agentainer_tpu.store import MemoryStore  # noqa: E402

SEED = int(os.environ.get("ATPU_FLEET_SEED", "1337"))
SMOKE = os.environ.get("ATPU_FLEET_SMOKE", "") not in ("", "0", "false")
TOKEN = "fleet-token"
AUTH = {"Authorization": f"Bearer {TOKEN}"}


class Stack:
    def __init__(self, tmpdir: str):
        self.tmpdir = tmpdir
        self.services = None
        self.client = None

    async def start(self) -> None:
        from aiohttp.test_utils import TestClient, TestServer

        os.environ["ATPU_JITTER_SEED"] = str(SEED)
        cfg = Config()
        cfg.auth_token = TOKEN
        cfg.cadences.replay_scan_s = 1.0
        cfg.cadences.state_sync_s = 2.0
        cfg.fleet.lease_interval_s = 0.25
        cfg.fleet.suspect_after_s = 1.0
        cfg.fleet.dead_after_s = 2.0
        backend = LocalBackend(data_dir=self.tmpdir, ready_timeout_s=90.0)
        self.services = build_services(
            config=cfg,
            store=MemoryStore(),
            backend=backend,
            console_logs=False,
            data_dir=self.tmpdir,
        )
        self.client = TestClient(TestServer(self.services.app))
        await self.client.start_server()
        backend.set_control(f"http://127.0.0.1:{self.client.server.port}", TOKEN)
        await start_background(self.services)

    async def stop(self) -> None:
        if self.services is not None:
            await stop_background(self.services)
            self.services.backend.close()
        if self.client is not None:
            await self.client.close()

    async def deploy(self, name: str, model, replicas: int, **kw) -> str:
        resp = await self.client.post(
            "/agents",
            json={"name": name, "model": model, "replicas": replicas, **kw},
            headers=AUTH,
        )
        doc = await resp.json()
        assert resp.status == 200, doc
        agent_id = doc["data"]["id"]
        resp = await self.client.post(f"/agents/{agent_id}/start", headers=AUTH)
        assert resp.status == 200, await resp.text()
        return agent_id

    async def remove(self, agent_id: str) -> None:
        await self.client.delete(f"/agents/{agent_id}", headers=AUTH)


async def closed_loop_burst(
    stack: Stack, agent_id: str, total: int, concurrency: int
) -> dict:
    """``total`` chats at fixed concurrency; per-request latency + goodput."""
    lat: list[float] = []
    errors = 0
    seq = 0
    lock = asyncio.Lock()

    async def worker():
        nonlocal seq, errors
        while True:
            async with lock:
                if seq >= total:
                    return
                seq += 1
                n = seq
            t0 = time.monotonic()
            resp = await stack.client.post(
                f"/agent/{agent_id}/chat",
                data=json.dumps(
                    {
                        "message": f"fleet-{SEED}-{n}",
                        "session": f"s{n % 16}",
                        "max_tokens": 8,
                        "ignore_eos": True,
                    }
                ),
            )
            await resp.read()
            if resp.status == 200:
                lat.append(time.monotonic() - t0)
            else:
                errors += 1

    t0 = time.monotonic()
    await asyncio.gather(*(worker() for _ in range(concurrency)))
    wall = time.monotonic() - t0
    lat.sort()
    return {
        "requests": total,
        "ok": len(lat),
        "errors": errors,
        "wall_s": round(wall, 3),
        "goodput_rps": round(len(lat) / wall, 2) if wall > 0 else 0.0,
        "ttft_p50_ms": round(1000 * percentile(lat, 0.50), 2) if lat else None,
        "ttft_p99_ms": round(1000 * percentile(lat, 0.99), 2) if lat else None,
    }


LLM_MODEL = {
    "engine": "llm",
    "config": "tiny",
    "options": {"max_batch": 4, "max_seq": 256, "prefill_chunk": 64},
}


async def _wait_loaded(stack: Stack, agent_id: str, cap_s: float = 120.0) -> None:
    rec = stack.services.manager.get_agent(agent_id)
    t0 = time.monotonic()
    for eid in rec.all_engine_ids():
        while time.monotonic() - t0 < cap_s:
            if (stack.services.backend.stats(eid) or {}).get("model_loaded"):
                break
            await asyncio.sleep(0.5)


async def tier_scaling(stack: Stack) -> dict:
    total = 24 if SMOKE else 120
    concurrency = 8
    out = {}
    for n in (1, 2, 4):
        agent_id = await stack.deploy(f"fleet-llm-{n}", LLM_MODEL, replicas=n)
        await _wait_loaded(stack, agent_id)
        # tiny warm pass so residual load/attach cost stays out of the sweep
        await closed_loop_burst(stack, agent_id, 4, 2)
        out[str(n)] = await closed_loop_burst(stack, agent_id, total, concurrency)
        await stack.remove(agent_id)
    return out


async def tier_failover_mttr(stack: Stack) -> dict:
    """Steady 50 Hz probes against a 2-replica fleet; SIGKILL one replica;
    MTTR = the longest observed gap between consecutive 200s around the
    kill (the caller-visible outage, not the process respawn time)."""
    agent_id = await stack.deploy("fleet-mttr", "echo", replicas=2, auto_restart=True)
    gaps: list[float] = []
    last_ok = time.monotonic()
    killed_at = None
    victim = stack.services.manager.get_agent(agent_id).all_engine_ids()[0]
    t_end = time.monotonic() + (6.0 if SMOKE else 12.0)
    while time.monotonic() < t_end:
        if killed_at is None and time.monotonic() > t_end - (5.0 if SMOKE else 9.0):
            killed_at = time.monotonic()
            stack.services.backend.kill_engine_hard(victim)
        resp = await stack.client.post(
            f"/agent/{agent_id}/chat", data=json.dumps({"message": "probe"})
        )
        await resp.read()
        now = time.monotonic()
        if resp.status == 200:
            gaps.append(now - last_ok)
            last_ok = now
        await asyncio.sleep(0.02)
    await stack.remove(agent_id)
    return {
        "killed": killed_at is not None,
        "probes_ok": len(gaps),
        "mttr_s": round(max(gaps), 3) if gaps else None,
    }


async def tier_token_identity(stack: Stack) -> dict:
    """Chaos-soak failover compressed: ctl turn1/2 clean; vic turn1, then
    its replica dies mid-decode of turn2; the settled turn2 must equal the
    control's bit for bit."""
    agent_id = await stack.deploy(
        "fleet-llm",
        {
            "engine": "llm",
            "config": "tiny",
            # plain decode: the kill must land mid-decode, not after a
            # spec-accelerated turn already finished (see chaos_soak.py)
            "options": {
                "max_batch": 2,
                "max_seq": 256,
                "prefill_chunk": 64,
                "kv_snapshot_interval_s": 0.5,
                "speculative": False,
            },
        },
        replicas=2,
        auto_restart=True,
        # same deterministic mid-decode window as the chaos soak: a
        # delay-only decode failpoint (symmetric, token-stream-neutral)
        env={"ATPU_FAULTS": "engine.decode_step:error=none,delay_ms=150"},
    )
    # both replicas must finish model load before the control turns
    rec = stack.services.manager.get_agent(agent_id)
    t_warm = time.monotonic()
    for eid in rec.all_engine_ids():
        while time.monotonic() - t_warm < 90.0:
            if (stack.services.backend.stats(eid) or {}).get("model_loaded"):
                break
            await asyncio.sleep(0.5)

    async def turn(session, message, n=12):
        resp = await stack.client.post(
            f"/agent/{agent_id}/chat",
            data=json.dumps(
                {"message": message, "session": session, "max_tokens": n, "ignore_eos": True}
            ),
        )
        doc = await resp.json()
        return resp.status, doc.get("response", ""), resp.headers.get(
            "X-Agentainer-Request-ID", ""
        )

    s, _, _ = await turn("ctl", "alpha alpha alpha")
    assert s == 200
    s, ctl_t2, _ = await turn("ctl", "beta beta", n=32)
    assert s == 200
    s, _, _ = await turn("vic", "alpha alpha alpha")
    assert s == 200
    kv_key = f"agent:{agent_id}:kvcache:vic"
    t0 = time.monotonic()
    while stack.services.store.get(kv_key) is None:
        if time.monotonic() - t0 > 45:
            return {"token_identical": False, "reason": "snapshot never landed"}
        await asyncio.sleep(0.25)
    router = stack.services.router
    with router._lock:
        victim = router._affinity.get((agent_id, "vic"), "")
    if not victim:
        return {"token_identical": False, "reason": "no affinity"}
    task = asyncio.ensure_future(turn("vic", "beta beta", n=32))
    await asyncio.sleep(0.25)
    t_kill = time.monotonic()
    stack.services.backend.kill_engine_hard(victim)
    status, live, rid = await task
    resumed = None
    if status == 200:
        resumed = live
    elif rid:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            req = stack.services.journal.get(agent_id, rid)
            if req is not None and req.status == "completed":
                import base64 as _b64

                body = _b64.b64decode((req.response or {}).get("body_b64", "") or "")
                try:
                    resumed = json.loads(body).get("response", "")
                except Exception:
                    resumed = ""
                break
            await asyncio.sleep(0.25)
    out = {
        "token_identical": resumed == ctl_t2,
        "mid_decode_status": status,
        "failover_settle_s": round(time.monotonic() - t_kill, 3),
    }
    await stack.remove(agent_id)
    return out


async def run_bench(tmpdir: str) -> dict:
    stack = Stack(tmpdir)
    try:
        await stack.start()
        scaling = await tier_scaling(stack)
        mttr = await tier_failover_mttr(stack)
        identity = await tier_token_identity(stack)
    finally:
        await stack.stop()
    return {"scaling": scaling, "failover": mttr, "resume": identity}


def main() -> int:
    t0 = time.monotonic()
    tmpdir = tempfile.mkdtemp(prefix="atpu-fleet-")
    result = asyncio.run(run_bench(tmpdir))
    base = result["scaling"]["1"]["goodput_rps"] or 1.0
    speedup4 = round((result["scaling"]["4"]["goodput_rps"] or 0.0) / base, 2)
    cores = len(os.sched_getaffinity(0))
    ok = (
        result["resume"].get("token_identical") is True
        and result["failover"].get("mttr_s") is not None
        and all(v["errors"] == 0 for v in result["scaling"].values())
    )
    doc = {
        # the robustness headline: caller-visible outage when a replica of
        # a 2-replica fleet is SIGKILLed under steady traffic (a survivor
        # keeps serving; compare engine_sigkill MTTR ~1s and llm respawn
        # ~2-3s in BENCH_chaos.json for the single-replica story)
        "metric": "fleet_failover_mttr_s",
        "value": result["failover"].get("mttr_s"),
        "unit": "s caller-visible gap, 2 replicas, one killed",
        "goodput_speedup_4x_replicas": speedup4,
        "host_cores": cores,
        # capacity scaling needs >= N cores (or N TPU hosts): replicas are
        # separate PROCESSES, so on a 1-core CI box the sweep measures
        # time-slicing overhead, not parallel capacity — the sweep is
        # recorded for the p99/goodput shape, the MTTR and token-identity
        # tiers are the hardware-independent assertions
        "scaling_note": (
            "positive goodput scaling requires >= replicas cores; "
            f"this host has {cores}"
        ),
        "seed": SEED,
        "smoke": SMOKE,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "pass": ok,
        **result,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    write_artifact("BENCH_fleet.json", doc)
    if not ok:
        print(f"FLEET BENCH FAILED: {json.dumps(result)[:600]}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
