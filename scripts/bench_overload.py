"""Overload benchmark: deadlines + shedding on vs off at 2-4x saturation.

A/B for the end-to-end deadline plane (ISSUE 3). The SAME engine config is
driven with open-loop Poisson-ish arrivals at a multiple of its measured
capacity, twice:

  off: ``deadlines=false``, no watermark — the historical behavior: every
       arrival queues, the backlog grows for the whole window, and most
       completions land long past the caller's patience;
  on:  ``deadlines=true`` + a submit-side shed watermark — excess arrivals
       get a fast EngineOverloaded (the proxy's 429) or expire in queue
       before prefill; admitted work completes inside its deadline.

Scored on GOODPUT — completions whose end-to-end latency fit the deadline,
per second of wall time until the system fully drains — plus p99 TTFT of
completed requests. Late completions are real work wasted on answers
nobody was waiting for; the off-mode pays for them in both metrics. A
steady-state single-lane pass guards that ``deadlines=false`` ITL is
unchanged (the deadline plane must cost nothing when disabled) and that
the enabled-but-unloaded engine matches it.

Runs on any JAX platform: the artifact under test is submit-path and
worker-loop policy, so a CPU run is a faithful A/B (absolute numbers are
smaller than on a tunneled TPU).

Usage: JAX_PLATFORMS=cpu python scripts/bench_overload.py
       ATPU_OVERLOAD_SMOKE=1 shortens every window (make overload).
Emits one JSON line on stdout; the committed artifact is
BENCH_overload.json.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, percentile as _p, steady_itl_interleaved

SMOKE = os.environ.get("ATPU_OVERLOAD_SMOKE", "") not in ("", "0", "false")
MODEL = os.environ.get("ATPU_OVL_MODEL", "tiny")
MAX_BATCH = int(os.environ.get("ATPU_OVL_MAX_BATCH", "4"))
MAX_TOKENS = int(os.environ.get("ATPU_OVL_MAX_TOKENS", "24"))
CAL_S = 2.0 if SMOKE else 4.0
WINDOW_S = 4.0 if SMOKE else 10.0
MULTS = [2.0] if SMOKE else [2.0, 4.0]
DRAIN_CAP_S = 60.0 if SMOKE else 180.0
PROMPT = "overload probe: how long is the queue today? "


def _mk_engine(deadlines: bool):
    return make_engine(
        MODEL,
        max_batch=MAX_BATCH,
        max_seq=512,
        decode_chunk=8,
        prefill_chunk=32,
        deadlines=deadlines,
        # admit up to ~2 batches of backlog, then shed — the engine-level
        # twin of the proxy's pending watermark
        shed_watermark=3 * MAX_BATCH if deadlines else 0,
    )


async def _steady_itl(engines: dict) -> dict[str, float]:
    return await steady_itl_interleaved(engines, passes=5, max_tokens=200)


async def _calibrate(eng) -> tuple[float, float]:
    """Closed-loop at capacity (max_batch clients): completions/s and mean
    latency — the denominators the overload multiples are defined against."""
    done = 0
    lat_sum = 0.0
    stop_at = time.monotonic() + CAL_S

    async def client(i: int) -> None:
        nonlocal done, lat_sum
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            await eng.generate(f"{PROMPT}cal{i}", max_tokens=MAX_TOKENS, temperature=0.0)
            lat_sum += time.monotonic() - t0
            done += 1

    t0 = time.monotonic()
    await asyncio.gather(*(client(i) for i in range(MAX_BATCH)))
    elapsed = time.monotonic() - t0
    return done / elapsed, (lat_sum / max(1, done)) * 1000


async def _overload_pass(eng, deadlines: bool, rps: float, deadline_ms: float) -> dict:
    """Open-loop arrivals at ``rps`` for WINDOW_S, then drain. Every arrival
    is classified: ok (completed within deadline), late, shed (fast 429
    analogue), expired (dead-lettered pre/mid-flight), error."""
    from agentainer_tpu.engine.llm import (
        EngineOverloaded,
        RequestCancelled,
        RequestExpired,
    )

    counts = {"ok": 0, "late": 0, "shed": 0, "expired": 0, "error": 0}
    ttfts: list[float] = []
    tasks = []
    t_start = time.monotonic()

    async def one(i: int) -> None:
        t0 = time.monotonic()
        dl = time.time() + deadline_ms / 1000.0 if deadlines else None
        try:
            r = await eng.generate(
                f"{PROMPT}ovl{i}", max_tokens=MAX_TOKENS, temperature=0.0, deadline_at=dl
            )
        except EngineOverloaded:
            counts["shed"] += 1
            return
        except (RequestExpired, RequestCancelled):
            counts["expired"] += 1
            return
        except Exception:
            counts["error"] += 1
            return
        latency_ms = 1000 * (time.monotonic() - t0)
        if r.get("ttft_ms") is not None:
            ttfts.append(r["ttft_ms"])
        counts["ok" if latency_ms <= deadline_ms else "late"] += 1

    i = 0
    gap = 1.0 / rps
    next_at = time.monotonic()
    while time.monotonic() - t_start < WINDOW_S:
        tasks.append(asyncio.ensure_future(one(i)))
        i += 1
        next_at += gap
        delay = next_at - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
    try:
        await asyncio.wait_for(asyncio.gather(*tasks), DRAIN_CAP_S)
    except asyncio.TimeoutError:
        for t in tasks:
            t.cancel()
    elapsed = time.monotonic() - t_start
    ttfts.sort()
    m = eng.metrics()
    return {
        "offered": i,
        "offered_rps": round(rps, 2),
        "window_s": WINDOW_S,
        "wall_s": round(elapsed, 2),
        "deadline_ms": round(deadline_ms, 1),
        **counts,
        "goodput_rps": round(counts["ok"] / elapsed, 3),
        "ttft_ms_p50": _p(ttfts, 0.5),
        "ttft_ms_p99": _p(ttfts, 0.99),
        "engine_shed_total": m["shed_total"],
        "engine_expired_total": m["expired_total"],
        "worker_errors": m["worker_errors"],
    }


async def run() -> dict:
    t0 = time.monotonic()
    import jax

    out: dict = {
        "metric": "llm_overload_goodput_shed_on_over_off",
        "unit": "ratio",
        "platform": jax.default_backend(),
        "model": MODEL,
        "max_batch": MAX_BATCH,
        "smoke": SMOKE,
        "passes": {},
    }
    engines = {}
    try:
        engines["off"] = _mk_engine(deadlines=False)
        engines["on"] = _mk_engine(deadlines=True)
        itls = await _steady_itl(engines)
        for mode, deadlines in (("off", False), ("on", True)):
            eng = engines[mode]
            cap_rps, mean_lat_ms = await _calibrate(eng)
            # the caller's patience: a few service times — generous at
            # capacity, hopeless once the backlog passes a few batches
            deadline_ms = max(250.0, 4 * mean_lat_ms)
            out["passes"][mode] = {
                "deadlines": deadlines,
                "itl_ms_steady": itls[mode],
                "capacity_rps": round(cap_rps, 3),
                "mean_latency_ms_at_capacity": round(mean_lat_ms, 1),
                "overload": {},
            }
            for mult in MULTS:
                out["passes"][mode]["overload"][f"{mult:g}x"] = await _overload_pass(
                    eng, deadlines, mult * cap_rps, deadline_ms
                )
    finally:
        for eng in engines.values():
            eng.shutdown()
    on2 = out["passes"]["on"]["overload"]["2x"]
    off2 = out["passes"]["off"]["overload"]["2x"]
    out["value"] = (
        round(on2["goodput_rps"] / off2["goodput_rps"], 3)
        if off2["goodput_rps"]
        else None
    )
    itl_on, itl_off = (
        out["passes"]["on"]["itl_ms_steady"],
        out["passes"]["off"]["itl_ms_steady"],
    )
    out["itl_steady_regression"] = (
        round(itl_on / itl_off - 1.0, 4) if itl_off else None
    )
    out["wall_s"] = round(time.monotonic() - t0, 1)
    return out


def main() -> None:
    out = asyncio.run(run())
    print(json.dumps(out), flush=True)
    # acceptance (ISSUE 3): shedding-on goodput >= shedding-off at >=2x
    # saturation; steady ITL within noise when the plane is off/idle
    on2 = out["passes"]["on"]["overload"]["2x"]
    off2 = out["passes"]["off"]["overload"]["2x"]
    ok = on2["goodput_rps"] >= off2["goodput_rps"] and (
        out["itl_steady_regression"] is None or out["itl_steady_regression"] < 0.10
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
