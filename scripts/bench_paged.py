"""Paged KV arena benchmark: resident capacity, zero-copy warm-prefix
TTFT, and the steady-ITL regression guard.

A/B for the block-table arena (engine/llm.py): the SAME tiny-engine config
is driven with ``paged_kv`` off (dense slots — the PR-2/PR-4 engine) and
on (page pool + block tables). Three tiers:

  resident capacity     — short agent sessions admitted one after another
                          at the SAME KV HBM budget (the paged pool defaults
                          to exactly the dense arena's bytes): dense caps at
                          max_batch residents (older sessions LRU-evict),
                          paged keeps hundreds-of-tokens sessions resident
                          until the POOL fills. Headline: resident sessions
                          at zero evictions / max_batch.
  warm-prefix TTFT      — probes sharing a ~1k-token persona, after the
                          first session populated the arena: dense FORKS a
                          compiled KV copy per admission (PR 2), paged maps
                          refcounted pages (zero KV copies — asserted via
                          the fork-path counter staying at 0 device copies).
  steady ITL            — uncontended long-generation decode, interleaved
                          across the engines (the <5% regression guard on
                          the gather/scatter attention path).

Host+device-graph behavior is platform-faithful on CPU (absolute numbers
shrink on a real chip; the RATIOS are the claim).

Usage: JAX_PLATFORMS=cpu python scripts/bench_paged.py
Emits one JSON line on stdout AND writes BENCH_paged.json at the repo root.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import (
    make_engine,
    p50 as _p50,
    steady_itl_interleaved,
    text_of_tokens,
    write_artifact,
)

MODEL = os.environ.get("ATPU_PAGED_MODEL", "tiny")
MAX_SEQ = int(os.environ.get("ATPU_PAGED_MAX_SEQ", "2048"))
MAX_BATCH = int(os.environ.get("ATPU_PAGED_MAX_BATCH", "4"))
PAGE_SIZE = int(os.environ.get("ATPU_PAGED_PAGE_SIZE", "64"))
PROBES = int(os.environ.get("ATPU_PAGED_PROBES", "12"))
SYS_TOKENS = int(os.environ.get("ATPU_PAGED_SYS_TOKENS", "1040"))
# per-session context of the capacity tier (tokens): agentic sessions idle
# between tool calls hold ~a few hundred tokens, not max_seq
SESSION_TOKENS = int(os.environ.get("ATPU_PAGED_SESSION_TOKENS", "120"))
CAPACITY_SESSIONS = int(os.environ.get("ATPU_PAGED_CAPACITY_SESSIONS", "48"))


def _mk_engine(paged: bool):
    opts = dict(
        max_batch=MAX_BATCH,
        max_seq=MAX_SEQ,
        decode_chunk=8,
        prefill_chunk=256,
    )
    if paged:
        opts.update(paged_kv=True, page_size=PAGE_SIZE)
    return make_engine(MODEL, **opts)


async def _capacity(eng, paged: bool) -> dict:
    """Admit short sessions until eviction starts (or the cap): how many
    stay resident at the dense-equivalent HBM budget?"""
    prompt = text_of_tokens(eng, SESSION_TOKENS - 16, "tool call result alpha beta. ")
    resident_peak = 0
    for i in range(CAPACITY_SESSIONS):
        await eng.chat(f"cap-{i}", prompt, max_tokens=8)
        if paged:
            resident = eng.metrics()["resident_sessions"]
        else:
            resident = len(eng.sessions)
        resident_peak = max(resident_peak, resident)
        if eng.session_evictions > 0:
            break
    return {
        "sessions_admitted": i + 1,
        "resident_peak_zero_eviction": resident_peak
        if eng.session_evictions == 0
        else resident_peak - 1,
        "session_evictions": eng.session_evictions,
        "session_tokens": SESSION_TOKENS,
    }


async def _warm_prefix(eng, paged: bool) -> dict:
    """Warm-prefix admission cost: dense forks a compiled copy, paged maps
    pages. KV copies are counted as device bytes the admission moved."""
    persona = text_of_tokens(
        eng, SYS_TOKENS, "You are agent seven of the fleet. Be concise and exact. "
    )
    await eng.generate(persona + " cold start", max_tokens=8)  # populate
    hbm0 = eng.hbm_bytes_read
    ttfts = []
    for k in range(PROBES):
        r = await eng.generate(
            f"{persona} user question {k} please answer", max_tokens=8
        )
        ttfts.append(r["ttft_ms"])
    m = eng.metrics()
    # bytes the warm admissions streamed MINUS what prefill+decode streamed
    # is noise-prone; the copy accounting is explicit instead: the dense
    # fork charges b×kv_bytes_per_pos per hit, the paged mapping charges
    # only a partial-tail page (zero here: 1040 ≥ 16 aligned pages)
    return {
        "ttft_ms_p50": _p50(ttfts),
        "ttft_samples": [round(x, 2) for x in ttfts],
        "prefix_hits": m["prefix_hits"],
        "prefix_tokens_saved": m["prefix_tokens_saved"],
        "fork_copy_bytes_est": (
            0
            if paged
            else int(m["prefix_tokens_saved"] * eng._kv_bytes_per_pos)
        ),
        "pages_shared": m.get("prefix_pages_shared_total", 0) if paged else None,
        "hbm_bytes_window": int(eng.hbm_bytes_read - hbm0),
    }


async def run() -> dict:
    t0 = time.monotonic()
    dense = _mk_engine(paged=False)
    paged = _mk_engine(paged=True)
    try:
        cap_dense = await _capacity(dense, paged=False)
        cap_paged = await _capacity(paged, paged=True)
        dense.clear_sessions()
        paged.clear_sessions()
        warm_dense = await _warm_prefix(dense, paged=False)
        warm_paged = await _warm_prefix(paged, paged=True)
        itl = await steady_itl_interleaved(
            {"dense": dense, "paged": paged}, passes=4, max_tokens=250
        )
        paged_m = paged.metrics()
        zero_copy = (
            warm_paged["prefix_hits"] > 0
            and paged._prefix_fork_fns == {}
            and warm_paged["pages_shared"] > 0
        )
        cap_ratio = round(
            cap_paged["resident_peak_zero_eviction"] / max(1, MAX_BATCH), 2
        )
        ttft_ratio = (
            round(warm_paged["ttft_ms_p50"] / warm_dense["ttft_ms_p50"], 3)
            if warm_dense["ttft_ms_p50"]
            else None
        )
        itl_reg = (
            round(itl["paged"] / itl["dense"] - 1.0, 4) if itl.get("dense") else None
        )
        import jax

        return {
            "metric": "paged_resident_capacity_over_max_batch",
            "value": cap_ratio,
            "unit": "ratio",
            "platform": jax.default_backend(),
            "model": MODEL,
            "max_batch": MAX_BATCH,
            "max_seq": MAX_SEQ,
            "page_size": PAGE_SIZE,
            "kv_pool_bytes": paged_m["kv_arena_bytes"],
            "dense_arena_bytes": dense.metrics()["kv_arena_bytes"],
            "capacity": {"dense": cap_dense, "paged": cap_paged},
            "warm_prefix": {"dense": warm_dense, "paged": warm_paged},
            "warm_prefix_ttft_paged_over_dense": ttft_ratio,
            "warm_prefix_zero_copy": zero_copy,
            "itl_ms_steady": itl,
            "itl_steady_regression": itl_reg,
            "paged_metrics": {
                k: paged_m[k]
                for k in (
                    "kv_pages_total",
                    "kv_pages_free",
                    "kv_fragmentation_pct",
                    "pages_truncated_total",
                    "prefix_pages_shared_total",
                    "page_exhausted_total",
                )
            },
            "worker_errors": dense.worker_errors + paged.worker_errors,
            "wall_s": round(time.monotonic() - t0, 1),
        }
    finally:
        dense.shutdown()
        paged.shutdown()


def main() -> None:
    out = asyncio.run(run())
    write_artifact("BENCH_paged.json", out)
    # acceptance guard (ISSUE 6): ≥4× max_batch residents at unchanged HBM,
    # warm-prefix admission did zero KV copies, steady ITL within 5%
    ok = (
        out["value"] is not None
        and out["value"] >= 4.0
        and out["warm_prefix_zero_copy"]
        and (out["itl_steady_regression"] is None or out["itl_steady_regression"] < 0.05)
        and out["worker_errors"] == 0
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
