"""Fused decode-loop benchmark: ITL + host syncs, fused_decode on vs off.

A/B for the fused on-device decode loop (engine/llm.py `_fused_fn`): the
SAME engine config is driven twice, once dispatching one compiled chunk
per readback (the per-chunk baseline) and once running the multi-step
``lax.while_loop`` with in-loop sampling and ONE readback per loop.

Three measurements:

  batch sweep — per-request decode ITL ((wall - TTFT) / (tokens - 1)) at
             batch 1 / 4 / max, ignore_eos so every lane runs its full
             budget (fixed-length: the pure dispatch-overhead A/B). The
             per-batch ``itl_ratio_fused_over_off_b{1,4,max}`` fields are
             first-class artifact outputs, each with an explicit <= 1.0
             acceptance bar: the dynamic-rung loop (one executable, nsteps
             a runtime operand up to the fused cap) covers a request's
             whole budget in a few long loops where the per-chunk baseline
             pays dispatch + readback every decode_chunk steps. A second
             sampled tier (temperature > 0) re-runs the mid batch through
             the in-loop sampler — recorded as
             ``itl_ratio_fused_over_off_sampled`` (no hard bar: sampling
             cost is shared by both modes, the ratio is tracked for
             drift);
  raw step — per-step wall of the bare jitted (forward + sample_step)
             body (cache donated, token fed back, best-of): the compute
             the loop repeats, with zero scheduling around it. The
             acceptance bar is fused batch-1 ITL p50 within 1.2x of this
             floor — i.e. dispatch + readback + host processing amortized
             over the loop cost < 20%;
  natural EOS — greedy requests that stop at a real EOS mid-loop: the
             per-lane EOS mask parks the lane and the whole-batch early
             exit lands the packed readback on the host a few forwards
             after the stop instead of a full chunk later — the worker's
             ready-poll processes the finish BEFORE dispatching another
             (stale) loop, so host syncs per token come out strictly
             below the per-chunk baseline, which keeps paying for its
             pipelined stale successors after the lane is done.

The artifact being measured is scheduler+compiled-graph behavior identical
on any JAX platform, so a CPU run is a faithful A/B (absolute numbers are
smaller than on a tunneled TPU, where every saved readback is a device
round-trip).

Usage: JAX_PLATFORMS=cpu python scripts/bench_decode_loop.py
       ATPU_DECODELOOP_SMOKE=1 shortens every pass (make decodeloop).
Emits one JSON line on stdout AND writes BENCH_decode_loop.json at the
repo root (the committed artifact).
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, p50, percentile, write_artifact

SMOKE = os.environ.get("ATPU_DECODELOOP_SMOKE", "") not in ("", "0", "false")
MODEL = os.environ.get("ATPU_DECODELOOP_MODEL", "tiny")
MAX_BATCH = int(os.environ.get("ATPU_DECODELOOP_MAX_BATCH", "8"))
MAX_TOKENS = int(os.environ.get("ATPU_DECODELOOP_MAX_TOKENS", "24" if SMOKE else "64"))
PASSES = int(os.environ.get("ATPU_DECODELOOP_PASSES", "2" if SMOKE else "4"))
EOS_REQS = int(os.environ.get("ATPU_DECODELOOP_EOS_REQS", "6" if SMOKE else "16"))
FWD_ITERS = int(os.environ.get("ATPU_DECODELOOP_FWD_ITERS", "40" if SMOKE else "200"))

BATCHES = [1, 4, MAX_BATCH]


def _mk_engine(fused: bool, **extra):
    return make_engine(
        MODEL,
        max_batch=MAX_BATCH,
        max_seq=256,
        decode_chunk=8,
        prefill_chunk=32,
        fused_decode=fused,
        # spec off: prompt-lookup rounds would absorb most decode steps on
        # these repetitive bench prompts and dilute the loop A/B to noise
        # (spec x fused composition is pinned by tests/test_fused_decode.py)
        speculative=False,
        **extra,
    )


def _decode_itl(r: dict, wall_ms: float):
    if r["completion_tokens"] < 2 or r.get("ttft_ms") is None:
        return None
    return (wall_ms - r["ttft_ms"]) / (r["completion_tokens"] - 1)


async def _batch_pass(eng, batch: int, temperature: float = 0.0) -> list[float]:
    """One concurrent wave of ``batch`` fixed-length requests."""

    async def one(i):
        t0 = time.monotonic()
        r = await eng.generate(
            f"decode loop lane {i}",
            max_tokens=MAX_TOKENS,
            temperature=temperature,
            top_p=0.9 if temperature > 0 else 1.0,
            ignore_eos=True,
        )
        return _decode_itl(r, 1000 * (time.monotonic() - t0))

    itls = await asyncio.gather(*(one(i) for i in range(batch)))
    return [x for x in itls if x is not None]


async def _sweep(eng) -> dict:
    out = {}
    for b in BATCHES:
        itls: list[float] = []
        for _ in range(PASSES):
            itls.extend(await _batch_pass(eng, b))
        s = sorted(itls)
        out[f"itl_ms_p50_b{b}"] = p50(itls)
        out[f"itl_ms_p99_b{b}"] = percentile(s, 0.99)
    # sampled tier: temperature > 0 lanes exercise the full in-loop sampler
    # (top-k/top-p filter + categorical draw per step) instead of the
    # greedy argmax fast path
    sampled: list[float] = []
    for _ in range(PASSES):
        sampled.extend(await _batch_pass(eng, min(4, MAX_BATCH), temperature=0.8))
    out["itl_ms_p50_sampled"] = p50(sampled)
    return out


async def _eos_pass(fused: bool, eos_tok: int) -> dict:
    """Sequential greedy requests on a tokenizer whose EOS is pinned to a
    token the model actually emits (the only way a random tiny model stops
    naturally). skip_warmup so the fused loop bakes the pinned id."""
    eng = _mk_engine(fused, skip_warmup=True)
    eng.tokenizer.eos_id = eos_tok
    try:
        toks = 0
        for i in range(EOS_REQS):
            r = await eng.generate(
                "stop at eos", max_tokens=MAX_TOKENS, temperature=0.0
            )
            toks += r["completion_tokens"]
        m = eng.metrics()
        return {
            "requests": EOS_REQS,
            "tokens": toks,
            "completion_tokens_p50": toks / EOS_REQS,
            "host_syncs_total": m["host_syncs_total"],
            "host_syncs_per_token": m["host_syncs_per_token"],
        }
    finally:
        eng.shutdown()


def _raw_step_ms(eng) -> float:
    """Per-step wall of the bare jitted loop body — single-token forward
    (full slot batch, the tensor shape every decode step runs) + the
    in-loop sampler, sampled token fed back, cache donated so the
    measurement doesn't pay an arena copy the serving path never pays.
    Chains the donated cache; only run right before shutdown."""
    import jax
    import jax.numpy as jnp

    from agentainer_tpu.engine.sampling import sample_step

    B = eng.max_batch
    key = jax.random.PRNGKey(0)

    # sampler knobs are jit ARGS, not closure constants: closed over, XLA
    # constant-folds the greedy case down to a bare argmax and the "floor"
    # stops measuring the step the serving loop actually runs
    def step(params, cache, tok, pos, temps, topk, topp):
        logits, cache = eng._run_forward(
            params, tok[:, None], pos[:, None], cache, None
        )
        nxt = sample_step(logits[:, 0], key, temps, topk, topp)
        return nxt.astype(jnp.int32), cache

    fwd = jax.jit(step, donate_argnums=(1,))
    tok = jnp.zeros((B,), jnp.int32)
    pos = jnp.zeros((B,), jnp.int32)
    temps = jnp.zeros((B,), jnp.float32)
    topk = jnp.zeros((B,), jnp.int32)
    topp = jnp.ones((B,), jnp.float32)
    cache = eng.cache
    # compile outside the clock
    tok, cache = fwd(eng.params, cache, tok, pos, temps, topk, topp)
    tok.block_until_ready()
    best = float("inf")
    burst = 10
    for _ in range(max(1, FWD_ITERS // burst)):
        t0 = time.monotonic()
        for _ in range(burst):
            tok, cache = fwd(eng.params, cache, tok, pos, temps, topk, topp)
        tok.block_until_ready()
        best = min(best, 1000 * (time.monotonic() - t0) / burst)
    return round(best, 4)


async def _measure(fused: bool) -> dict:
    eng = _mk_engine(fused)
    try:
        syncs0 = eng.metrics()["host_syncs_total"]
        toks0 = eng.tokens_generated
        sweep = await _sweep(eng)
        m = eng.metrics()
        fixed_syncs_per_token = round(
            (m["host_syncs_total"] - syncs0) / max(1, eng.tokens_generated - toks0), 4
        )
        out = {
            "fused_decode": fused,
            **sweep,
            "host_syncs_per_token_fixed_len": fixed_syncs_per_token,
            "fused_loops_total": m["fused_loops_total"],
            "fused_steps_total": m["fused_steps_total"],
            "fused_early_exits_total": m["fused_early_exits_total"],
            "fused_exit_reason_hist": m["fused_exit_reason_hist"],
            "worker_errors": m["worker_errors"],
        }
        if not fused:
            out["raw_step_ms"] = _raw_step_ms(eng)
        return out
    finally:
        eng.shutdown()


async def run() -> dict:
    t0 = time.monotonic()
    base = await _measure(fused=False)
    fused = await _measure(fused=True)

    # pin the natural-EOS token from a greedy probe: the 3rd generated
    # token, so the stop lands INSIDE the first fused loop (chunk 8)
    probe = _mk_engine(False, skip_warmup=True)
    try:
        ref = await probe.generate(
            "stop at eos", max_tokens=8, temperature=0.0, ignore_eos=True
        )
        eos_tok = int(ref["tokens"][2])
    finally:
        probe.shutdown()
    eos_base = await _eos_pass(False, eos_tok)
    eos_fused = await _eos_pass(True, eos_tok)

    import jax

    raw = base.get("raw_step_ms")
    b1 = fused.get("itl_ms_p50_b1")

    def _ratio(key: str):
        f, o = fused.get(key), base.get(key)
        return round(f / o, 3) if (f and o) else None

    out = {
        "metric": "llm_fused_decode_itl_p50_b1_over_raw_step",
        "value": round(b1 / raw, 3) if (b1 and raw) else None,
        "unit": "ratio",
        # first-class per-batch fused/off ITL ratios, each barred <= 1.0
        **{
            f"itl_ratio_fused_over_off_b{b}": _ratio(f"itl_ms_p50_b{b}")
            for b in BATCHES
        },
        "itl_ratio_fused_over_off_sampled": _ratio("itl_ms_p50_sampled"),
        "syncs_per_token_fused": fused["host_syncs_per_token_fixed_len"],
        "syncs_per_token_off": base["host_syncs_per_token_fixed_len"],
        "eos_syncs_per_token_fused": eos_fused["host_syncs_per_token"],
        "eos_syncs_per_token_off": eos_base["host_syncs_per_token"],
        "platform": jax.default_backend(),
        "model": MODEL,
        "smoke": SMOKE,
        "max_tokens": MAX_TOKENS,
        "batches": BATCHES,
        "off": base,
        "fused": fused,
        "eos_off": eos_base,
        "eos_fused": eos_fused,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    return out


def main() -> None:
    out = asyncio.run(run())
    write_artifact("BENCH_decode_loop.json", out)
    # acceptance guards: fused batch-1 decode ITL p50 within 1.2x of the
    # raw per-step floor; fused ITL p50 no worse than the per-chunk
    # baseline at EVERY batch size (the dynamic-rung loop must win, not
    # merely amortize); host syncs per token strictly below baseline on
    # the natural-EOS workload (early exit's stale-dispatch savings).
    # The fixed-length sync ratio is recorded but NOT barred: dispatch
    # counts there are equal by arithmetic, so the old <= guard could
    # never fail — vacuous bars are worse than no bars.
    ratios = [out[f"itl_ratio_fused_over_off_b{b}"] for b in BATCHES]
    ok = (
        out["value"] is not None
        and out["value"] <= 1.2
        and all(r is not None and r <= 1.0 for r in ratios)
        and out["eos_syncs_per_token_fused"] is not None
        and out["eos_syncs_per_token_off"] is not None
        and out["eos_syncs_per_token_fused"] < out["eos_syncs_per_token_off"]
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
