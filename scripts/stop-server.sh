#!/usr/bin/env bash
# Graceful shutdown of the control-plane daemon and its engine
# subprocesses. (Reference role: scripts/stop-server.sh.)
set -euo pipefail

ATPU_DATA_DIR="${ATPU_DATA_DIR:-$HOME/.agentainer}"
PIDFILE="$ATPU_DATA_DIR/agentainer.pid"

if [[ ! -f "$PIDFILE" ]]; then
    echo "not running (no $PIDFILE)"
    exit 0
fi
PID=$(cat "$PIDFILE")
if ! kill -0 "$PID" 2>/dev/null; then
    echo "stale pidfile removed"
    rm -f "$PIDFILE"
    exit 0
fi
kill "$PID"   # SIGTERM: daemon stops engines (SIGTERM→10s→SIGKILL) then exits
for _ in $(seq 1 100); do
    kill -0 "$PID" 2>/dev/null || { rm -f "$PIDFILE"; echo "stopped"; exit 0; }
    sleep 0.2
done
echo "did not exit after 20s; forcing" >&2
kill -9 "$PID" 2>/dev/null || true
rm -f "$PIDFILE"
