"""Shared helpers for the engine A/B benchmarks (bench_admission.py,
bench_prefix.py, bench_overload.py, bench_spec.py).

Every bench follows the same shape: build the SAME tiny-engine config
twice with one policy flag flipped, drive identical async client traffic
against both, report percentiles + a headline ratio, and write a one-line
JSON artifact at the repo root. The percentile/engine/steady-ITL/prompt
plumbing lives here so a new bench adds only its workload.
"""

from __future__ import annotations

import json
import os
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def percentile(sorted_xs: list, q: float, ndigits: int = 3):
    """q-th percentile of an ALREADY-SORTED list (None when empty — a
    zero-probe env override must not crash the bench)."""
    if not sorted_xs:
        return None
    return round(sorted_xs[min(len(sorted_xs) - 1, int(q * len(sorted_xs)))], ndigits)


def p50(xs: list, ndigits: int = 3):
    """Median of an unsorted list (None when empty)."""
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[len(xs) // 2], ndigits)


def make_engine(model: str = "tiny", **options):
    """One benchmark engine (warmup included, so measurements never pay a
    compile). Callers pass the policy flag under test plus sizing."""
    from agentainer_tpu.engine.llm import LLMEngine

    return LLMEngine.create(model, options=options)


def text_of_tokens(eng, n_tokens: int, phrase: str) -> str:
    """Grow a repeated phrase until it encodes to >= n_tokens."""
    reps = max(1, n_tokens // max(1, len(eng.tokenizer.encode(phrase))))
    text = phrase * reps
    while len(eng.tokenizer.encode(text)) < n_tokens:
        text += phrase
    return text


async def steady_itl(
    eng,
    passes: int = 2,
    max_tokens: int = 300,
    prompt: str = "steady state pass",
    temperature: float = 0.0,
) -> float:
    """Uncontended single-lane wall-clock ms per generated token, best of
    ``passes`` (a p50 over a handful of chunk samples is too noisy for a
    <5% regression check on a shared host)."""
    best = float("inf")
    for _ in range(passes):
        t0 = time.monotonic()
        r = await eng.generate(prompt, max_tokens=max_tokens, temperature=temperature)
        best = min(best, 1000 * (time.monotonic() - t0) / max(1, r["completion_tokens"]))
    return round(best, 3)


async def steady_itl_interleaved(
    engines: dict,
    passes: int = 5,
    max_tokens: int = 200,
    prompt: str = "steady state pass",
) -> dict[str, float]:
    """Best-of steady ITL per engine, INTERLEAVED across the set:
    back-to-back rounds on a shared host cancel the machine-noise that
    sequential measurement (engine A's passes minutes before engine B's)
    cannot — the regression guard compares policy, not the host's mood."""
    best: dict[str, float] = {}
    for _ in range(passes):
        for mode, eng in engines.items():
            t0 = time.monotonic()
            r = await eng.generate(prompt, max_tokens=max_tokens, temperature=0.0)
            per_tok = 1000 * (time.monotonic() - t0) / max(1, r["completion_tokens"])
            best[mode] = min(best.get(mode, per_tok), per_tok)
    return {mode: round(v, 3) for mode, v in best.items()}


def write_artifact(filename: str, doc: dict) -> str:
    """Print the one-line JSON (the driver scrapes stdout) AND write the
    committed artifact at the repo root. Returns the line."""
    line = json.dumps(doc)
    print(line, flush=True)
    with open(os.path.join(REPO_ROOT, filename), "w") as f:
        f.write(line + "\n")
    return line
