"""Self-speculative decoding benchmark: steady decode ITL, spec on vs off.

A/B for prompt-lookup speculation (engine/llm.py): the SAME engine config
is driven twice, once with ``speculative`` off (one model forward per
token per lane — the pre-spec engine) and once with it on (host-side
n-gram drafts verified by one batched multi-token forward per round).
Three workloads, each measuring per-request decode ITL ((wall - TTFT) /
(tokens - 1), so prefill never pollutes the decode comparison):

  json     — a tool-call JSON loop: the agentic best case, the generated
             stream constantly re-emits spans already in context, drafts
             fill the verify bucket and mostly accept;
  chat     — flattened-history turns (persona + growing history, gemini
             style): the prompt carries prior replies, so re-emitted
             spans draft well even though each turn's tail is fresh;
  adversarial — temperature-1 sampling from random-soup prompts: ~no
             n-gram repeats, drafts mostly never fire (lookup-miss
             backoff) and any that do are rejected (acceptance-EMA
             collapse) — this workload must stay within noise of the
             spec-off baseline, with the collapse visible in metrics.

The artifact being measured is scheduler+compiled-graph behavior identical
on any JAX platform, so a CPU run is a faithful A/B (absolute numbers are
smaller than on a tunneled TPU, where each saved forward is a full chunk
wall).

Usage: JAX_PLATFORMS=cpu python scripts/bench_spec.py
       ATPU_SPEC_SMOKE=1 shortens every pass (make spec).
Emits one JSON line on stdout AND writes BENCH_spec.json at the repo root
(the committed artifact).
"""

from __future__ import annotations

import asyncio
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, p50, write_artifact

SMOKE = os.environ.get("ATPU_SPEC_SMOKE", "") not in ("", "0", "false")
MODEL = os.environ.get("ATPU_SPEC_MODEL", "tiny")
REQS = int(os.environ.get("ATPU_SPEC_REQS", "4" if SMOKE else "10"))
MAX_TOKENS = int(os.environ.get("ATPU_SPEC_MAX_TOKENS", "64" if SMOKE else "128"))
CHAT_TURNS = int(os.environ.get("ATPU_SPEC_CHAT_TURNS", "4" if SMOKE else "6"))

JSON_CALL = '{"tool": "search", "args": {"query": "status", "limit": 5}, "id": %d}\n'


def _mk_engine(speculative: bool):
    return make_engine(
        MODEL,
        max_batch=4,
        max_seq=1024,
        decode_chunk=8,
        prefill_chunk=256,
        speculative=speculative,
    )


def _decode_itl(r: dict, wall_ms: float):
    if r["completion_tokens"] < 2 or r.get("ttft_ms") is None:
        return None
    return (wall_ms - r["ttft_ms"]) / (r["completion_tokens"] - 1)


async def _one(eng, prompt: str, temperature: float = 0.0):
    t0 = time.monotonic()
    r = await eng.generate(prompt, max_tokens=MAX_TOKENS, temperature=temperature)
    return _decode_itl(r, 1000 * (time.monotonic() - t0))


async def _json_pass(eng) -> list[float]:
    """Sequential tool-call-loop requests, each a fresh context."""
    itls = []
    for i in range(REQS):
        itl = await _one(eng, JSON_CALL % i + JSON_CALL % (i + 1) + JSON_CALL % i)
        if itl is not None:
            itls.append(itl)
    return itls


async def _chat_pass(eng) -> list[float]:
    """Flattened-history turns: persona + growing history, fresh generate
    per turn (the assistant flavor's serving shape)."""
    persona = "You are a terse and careful fleet agent. Answer exactly. "
    itls = []
    history: list[str] = []
    for t in range(CHAT_TURNS):
        prompt = (
            persona
            + "\n".join(history)
            + f"\nUser: run tool pass {t}\nAssistant:"
        )
        t0 = time.monotonic()
        r = await eng.generate(prompt, max_tokens=MAX_TOKENS, temperature=0.0)
        itl = _decode_itl(r, 1000 * (time.monotonic() - t0))
        if itl is not None:
            itls.append(itl)
        history.append(f"User: run tool pass {t}")
        history.append(f"Assistant: {r['text'][:120]}")
    return itls


async def _adversarial_pass(eng) -> list[float]:
    """Random-soup prompts at temperature 1: no exploitable repetition.
    Must degrade to the plain ladder (graceful), not tax it."""
    rng = random.Random(0)
    alphabet = "abcdefghijklmnopqrstuvwxyz0123456789 "
    itls = []
    for _ in range(REQS):
        prompt = "".join(rng.choice(alphabet) for _ in range(120))
        itl = await _one(eng, prompt, temperature=1.0)
        if itl is not None:
            itls.append(itl)
    return itls


async def _measure(speculative: bool) -> dict:
    eng = _mk_engine(speculative)
    try:
        json_itls = await _json_pass(eng)
        chat_itls = await _chat_pass(eng)
        m_mid = eng.metrics()
        adv_itls = await _adversarial_pass(eng)
        m = eng.metrics()
        return {
            "speculative": speculative,
            "itl_ms_p50_json": p50(json_itls),
            "itl_ms_p50_chat": p50(chat_itls),
            "itl_ms_p50_adversarial": p50(adv_itls),
            "json_samples": [round(x, 3) for x in json_itls],
            "chat_samples": [round(x, 3) for x in chat_itls],
            "adversarial_samples": [round(x, 3) for x in adv_itls],
            "spec_rounds": m["spec_rounds"],
            "spec_drafted": m["spec_drafted"],
            "spec_accepted": m["spec_accepted"],
            "spec_rejected": m["spec_rejected"],
            "spec_acceptance_rate": m["spec_acceptance_rate"],
            "spec_verify_hist": m["spec_verify_hist"],
            # gamma collapse visibility: rounds stop advancing during the
            # adversarial pass while the EMA floor shows per slot
            "spec_rounds_during_adversarial": m["spec_rounds"]
            - m_mid["spec_rounds"],
            "spec_slot_acceptance_after_adversarial": m["spec_slot_acceptance"],
            "worker_errors": m["worker_errors"],
        }
    finally:
        eng.shutdown()


async def run() -> dict:
    t0 = time.monotonic()
    base = await _measure(speculative=False)
    spec = await _measure(speculative=True)
    import jax

    def ratio(key):
        if base[key] and spec[key] is not None:
            return round(spec[key] / base[key], 3)
        return None

    out = {
        "metric": "llm_spec_decode_itl_p50_spec_over_off_json",
        "value": ratio("itl_ms_p50_json"),
        "unit": "ratio",
        "chat_ratio": ratio("itl_ms_p50_chat"),
        "adversarial_ratio": ratio("itl_ms_p50_adversarial"),
        "platform": jax.default_backend(),
        "model": MODEL,
        "smoke": SMOKE,
        "requests_per_pass": REQS,
        "max_tokens": MAX_TOKENS,
        "off": base,
        "speculative": spec,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    return out


def main() -> None:
    out = asyncio.run(run())
    write_artifact("BENCH_spec.json", out)
    # acceptance guard (ISSUE 4): steady decode ITL >= 1.5x faster (ratio
    # <= 1/1.5) on the JSON tool-call loop; adversarial within 5% of the
    # spec-off baseline (graceful degradation)
    ok = (
        out["value"] is not None
        and out["value"] <= 1 / 1.5
        and (
            out["adversarial_ratio"] is None or out["adversarial_ratio"] <= 1.05
        )
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
