"""Chaos soak: a live daemon + real engine subprocesses driven through a
SEEDED fault schedule, asserting the resilience invariants end to end.

The five mechanisms behind the durability guarantee (journal, replay,
health, reconciler, deadline plane — docs/RESILIENCE.md) are each unit-
tested, but control-plane/data-plane reliability splits break down where
they *cooperate* under failure. This harness runs the real stack —
control plane, proxy, journal, replay worker, restart watcher, engine
subprocesses — through deterministic fault phases:

  engine_sigkill    SIGKILL the echo engine mid-traffic (watcher respawn,
                    crash heuristic, replay drain)
  store_blip        seeded-probability store.get/set failpoints (breaker,
                    serve-through degradation, loop survival)
  slow_dispatch     proxy.dispatch delay failpoint (latency, not loss)
  poisoned_prefill  engine.prefill failpoint inside a real LLM engine
                    subprocess: the typed poison signal dead-letters the
                    failing request after two fast strikes (reason
                    recorded, requeue-able) while the engine survives and
                    keeps serving the healthy traffic behind it
  llm_sigkill       SIGKILL the LLM host process, then token-identical
                    session resume from the KV snapshot
  fused_inject      SIGKILL a fused+in-loop-spec engine while a second
                    session's lane is STAGED into the running loop and the
                    loop carries unverified device drafts: both journaled
                    turns settle token-identical on the respawned engine
  replica_failover  2-replica LLM fleet: SIGKILL the replica serving a
                    session MID-DECODE; the journaled turn settles on the
                    SURVIVOR with a token-identical continuation (restored
                    from the store-durable snapshot), and the next live
                    turn matches the control session bit for bit
  stream_kill       SIGKILL the replica serving a live SSE stream
                    mid-decode: the client's single connection sees one
                    gapless, duplicate-free offset sequence bit-for-bit
                    equal to the undisturbed control — the proxy splices
                    the survivor's stream at last_acked_offset + 1
  lease_flap        replica.lease failpoint starves heartbeat refreshes on
                    a healthy 2-replica echo fleet: replicas flap SUSPECT
                    (excluded from routing) and return ALIVE when the
                    budget is spent — service never degrades below 200s
  route_dead        router.pick failpoint returns stale (dead) replica
                    choices while one echo replica is down: the bounded
                    retry-on-next-replica absorbs every stale pick
  torn_aof          truncate the native store's AOF mid-record; reopen
                    recovers every complete record and keeps appending

Invariants asserted (exit nonzero on violation):

  * no acked request lost — every 202-acked id settles COMPLETED, every
    200 was delivered synchronously;
  * no double execution — no chat message appears twice in the agent's
    recorded history, acked ones appear exactly once;
  * journal pending converges to 0 for every agent;
  * sessions resume token-identical after an engine SIGKILL;
  * per-fault-class recovery time (MTTR) is recorded.

Deterministic: the schedule, failpoint probabilities, and traffic are all
derived from ATPU_CHAOS_SEED (default 1337). ATPU_CHAOS_SMOKE=1 shortens
traffic volumes (make chaos). Emits one JSON line; the committed artifact
is BENCH_chaos.json.

Usage: JAX_PLATFORMS=cpu python scripts/chaos_soak.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from _benchlib import write_artifact  # noqa: E402

from agentainer_tpu import faults  # noqa: E402
from agentainer_tpu.config import Config  # noqa: E402
from agentainer_tpu.daemon import (  # noqa: E402
    build_services,
    start_background,
    stop_background,
)
from agentainer_tpu.runtime.local import LocalBackend  # noqa: E402
from agentainer_tpu.store import MemoryStore  # noqa: E402

SEED = int(os.environ.get("ATPU_CHAOS_SEED", "1337"))
SMOKE = os.environ.get("ATPU_CHAOS_SMOKE", "") not in ("", "0", "false")
TOKEN = "chaos-token"
AUTH = {"Authorization": f"Bearer {TOKEN}"}
RECOVERY_CAP_S = 90.0


class Soak:
    def __init__(self, tmpdir: str):
        self.tmpdir = tmpdir
        self.services = None
        self.client = None
        self.seq = 0
        # message -> ack kind ("sync" 200 | "queued" 202 rid | "refused")
        self.acks: dict[str, dict] = {}
        self.mttr: dict[str, float] = {}
        self.counts = {"sent": 0, "ok": 0, "queued": 0, "refused": 0, "error5xx": 0}
        self.violations: list[str] = []

    # -- stack lifecycle --------------------------------------------------
    async def start(self) -> None:
        from aiohttp.test_utils import TestClient, TestServer

        cfg = Config()
        cfg.auth_token = TOKEN
        # tight cadences so the soak observes recovery, not scan timers
        cfg.cadences.replay_scan_s = 1.0
        cfg.cadences.state_sync_s = 2.0
        cfg.cadences.metrics_interval_s = 5.0
        cfg.resilience.restart_backoff_base_s = 0.2
        cfg.resilience.breaker_cooldown_s = 0.5
        # fleet: tight lease windows so replica death detection is observed
        # within the soak's budget, not the production 3s/6s defaults.
        # fleet.replicas stays 1 — only the explicitly-pinned fleet agents
        # run multi-replica, every other agent is the pre-fleet baseline.
        cfg.fleet.lease_interval_s = 0.25
        cfg.fleet.suspect_after_s = 1.0
        cfg.fleet.dead_after_s = 2.0
        # SSE token streaming through the proxy: the stream_kill phase
        # asserts the mid-stream failover splice end to end
        cfg.features.streaming = True
        os.environ["ATPU_JITTER_SEED"] = str(SEED)
        backend = LocalBackend(
            data_dir=self.tmpdir,
            ready_timeout_s=90.0,
            restart_backoff_base_s=cfg.resilience.restart_backoff_base_s,
            restart_backoff_max_s=2.0,
            restart_window_s=cfg.resilience.restart_window_s,
            restart_max_rapid=cfg.resilience.restart_max_rapid,
        )
        self.services = build_services(
            config=cfg,
            store=MemoryStore(),
            backend=backend,
            console_logs=False,
            data_dir=self.tmpdir,
        )
        self.client = TestClient(TestServer(self.services.app))
        await self.client.start_server()
        backend.set_control(f"http://127.0.0.1:{self.client.server.port}", TOKEN)
        await start_background(self.services)

    async def stop(self) -> None:
        faults.disarm_all()
        if self.services is not None:
            await stop_background(self.services)
            self.services.backend.close()
        if self.client is not None:
            await self.client.close()

    async def deploy(
        self, name: str, model, auto_restart: bool = True, env=None, replicas: int = 0
    ) -> str:
        resp = await self.client.post(
            "/agents",
            json={
                "name": name,
                "model": model,
                "auto_restart": auto_restart,
                "env": env or {},
                "replicas": replicas,
            },
            headers=AUTH,
        )
        doc = await resp.json()
        assert resp.status == 200, doc
        agent_id = doc["data"]["id"]
        resp = await self.client.post(f"/agents/{agent_id}/start", headers=AUTH)
        assert resp.status == 200, await resp.text()
        return agent_id

    # -- traffic ----------------------------------------------------------
    async def chat(self, agent_id: str, track: bool = True, session: str | None = None):
        """One proxied chat with a unique message; records the ack kind."""
        self.seq += 1
        msg = f"chaos-{SEED}-{self.seq}"
        body = {"message": msg}
        if session is not None:
            body["session"] = session
        resp = await self.client.post(
            f"/agent/{agent_id}/chat", data=json.dumps(body)
        )
        raw = await resp.read()
        self.counts["sent"] += 1
        rec = {"status": resp.status, "agent_id": agent_id, "rid": ""}
        if resp.status == 200:
            self.counts["ok"] += 1
            rec["kind"] = "sync"
        elif resp.status == 202:
            self.counts["queued"] += 1
            rec["kind"] = "queued"
            try:
                rec["rid"] = json.loads(raw)["data"]["request_id"]
            except Exception:
                pass
        elif resp.status >= 500 or resp.status == 429:
            self.counts["refused"] += 1
            if resp.status >= 500:
                self.counts["error5xx"] += 1
            rec["kind"] = "refused"
        if track:
            self.acks[msg] = rec
        return resp.status, msg

    async def probe_until_ok(self, agent_id: str, label: str) -> float:
        """MTTR probe: wall time until the agent serves a 200 again."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            status, _ = await self.chat(agent_id, track=True)
            if status == 200:
                mttr = time.monotonic() - t0
                self.mttr[label] = round(mttr, 3)
                return mttr
            await asyncio.sleep(0.2)
        self.violations.append(f"{label}: no recovery within {RECOVERY_CAP_S}s")
        self.mttr[label] = -1.0
        return -1.0

    async def drain_pending(self, agent_id: str, cap_s: float = 45.0) -> bool:
        """Wait for the replay worker to drain the agent's queue to 0."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < cap_s:
            stats = self.services.journal.stats(agent_id)
            if stats["pending"] == 0:
                return True
            await asyncio.sleep(0.25)
        return False

    # -- phases -----------------------------------------------------------
    async def phase_baseline(self, echo_id: str, n: int) -> None:
        for _ in range(n):
            status, msg = await self.chat(echo_id)
            if status != 200:
                self.violations.append(f"baseline: {msg} got {status}")

    async def phase_engine_sigkill(self, echo_id: str) -> None:
        engine_id = self.services.manager.get_agent(echo_id).engine_id
        self.services.backend.kill_engine_hard(engine_id)
        # fire into the dead window: these ack 502 (left pending) or 202
        for _ in range(3):
            await self.chat(echo_id)
            await asyncio.sleep(0.05)
        await self.probe_until_ok(echo_id, "engine_sigkill")

    async def phase_store_blip(self, echo_id: str, n: int) -> None:
        # seeded 50% store read/write failures, budget-bounded so the blip
        # ENDS deterministically even under the background loops' traffic
        faults.arm("store.get", error="ConnectionError", probability=0.5, seed=SEED, count=60)
        faults.arm("store.set", error="ConnectionError", probability=0.5, seed=SEED + 1, count=40)
        t0 = time.monotonic()
        for _ in range(n):
            await self.chat(echo_id)
            await asyncio.sleep(0.05)
        # burn any remaining budget through the store, then disarm
        while any(fp["count"] != 0 for fp in faults.active()):
            try:
                self.services.store.get("chaos:burn")
                self.services.store.set("chaos:burn", "x")
            except ConnectionError:
                pass
            await asyncio.sleep(0)  # the background loops keep breathing
            if time.monotonic() - t0 > 30:
                break
        faults.disarm_all()
        await self.probe_until_ok(echo_id, "store_blip")

    async def phase_slow_dispatch(self, echo_id: str, n: int) -> None:
        faults.arm("proxy.dispatch", error="none", delay_ms=250, count=n)
        t0 = time.monotonic()
        for _ in range(n):
            status, msg = await self.chat(echo_id)
            if status != 200:
                self.violations.append(f"slow_dispatch: {msg} got {status}")
        faults.disarm_all()
        self.mttr["slow_dispatch"] = round((time.monotonic() - t0) / max(1, n), 3)

    async def phase_poisoned_prefill(self, poison_id: str) -> bool:
        """One deterministically failing request (engine.prefill armed with
        count=2) on a HEALTHY engine. Repair-path contract: the engine's
        typed poison signal (PREFILL_POISON_HEADER on the 500) charges the
        tightened poison budget instead of archiving the 500 or walking the
        full retry ladder — the entry dead-letters in seconds with the
        reason recorded, stays requeue-able, and the engine serves the
        traffic behind it throughout. MTTR here is first-5xx → dead-letter:
        the repair decision latency, not the model-load wall clock the old
        probe conflated it with."""
        agent = self.services.manager.get_agent(poison_id)
        t_warm = time.monotonic()
        while time.monotonic() - t_warm < RECOVERY_CAP_S:
            stats = self.services.backend.stats(agent.engine_id) or {}
            if stats.get("model_loaded"):
                break
            await asyncio.sleep(0.5)
        else:
            self.violations.append("poisoned_prefill: engine never loaded")
            self.mttr["poisoned_prefill"] = -1.0
            return False
        resp = await self.client.post(
            f"/agent/{poison_id}/chat",
            data=json.dumps({"message": f"poison-{SEED}"}),
        )
        await resp.read()
        t0 = time.monotonic()
        rid = resp.headers.get("X-Agentainer-Request-ID", "")
        if resp.status < 500 or not rid:
            self.violations.append(
                f"poisoned_prefill: failpoint never fired (got {resp.status})"
            )
            self.mttr["poisoned_prefill"] = -1.0
            return False
        # strike 1 was the live dispatch; the next replay tick is strike 2
        req = None
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            req = self.services.journal.get(poison_id, rid)
            if req is not None and req.status == "failed":
                break
            await asyncio.sleep(0.05)
        if req is None or req.status != "failed":
            self.violations.append(
                "poisoned_prefill: entry never dead-lettered "
                f"({None if req is None else req.status})"
            )
            self.mttr["poisoned_prefill"] = -1.0
            return False
        self.mttr["poisoned_prefill"] = round(time.monotonic() - t0, 3)
        ok = True
        if "poisoned prefill" not in (req.error or ""):
            self.violations.append(
                f"poisoned_prefill: reason not recorded ({req.error!r})"
            )
            ok = False
        # the dead letter is an operator artifact: requeue must revive it,
        # and with the failpoint's count=2 consumed it now completes
        if self.services.journal.requeue(poison_id, rid) is None:
            self.violations.append("poisoned_prefill: dead letter not requeue-able")
            ok = False
        else:
            t_rq = time.monotonic()
            while time.monotonic() - t_rq < RECOVERY_CAP_S:
                req = self.services.journal.get(poison_id, rid)
                if req is not None and req.status == "completed":
                    break
                await asyncio.sleep(0.25)
            if req is None or req.status != "completed":
                self.violations.append(
                    "poisoned_prefill: requeued entry never completed "
                    f"({None if req is None else req.status})"
                )
                ok = False
        # the engine was healthy the whole time: live traffic still serves
        status, _ = await self.chat(poison_id, track=False)
        if status != 200:
            self.violations.append(
                f"poisoned_prefill: healthy traffic got {status} after dead-letter"
            )
            ok = False
        return ok

    async def phase_page_exhaustion(self, paged_id: str) -> bool:
        """Paged-KV backpressure invariant: the paged agent runs a tiny
        page pool AND its engine armed engine.page_alloc (count=1) from its
        env, so both injected and ORGANIC pool exhaustion fire during this
        phase. Every exhaustion must surface as 429/202 backpressure —
        journal entries stay replayable (no acked loss, settled like any
        other phase's traffic) — never a 5xx crash; the engine serves on
        and its metrics count the exhaustions."""
        # the paged engine may still be LOADING (five tiny-LLM hosts boot
        # concurrently in this soak): wait until the model is loaded before
        # asserting on backpressure — a 502 during model load is the
        # loading contract, not a pool-exhaustion crash. Readiness is read
        # from /metrics, NOT by serving probe chats: a probe would burn the
        # armed engine.page_alloc fire budget before the phase's own
        # traffic gets to observe the injected exhaustion.
        agent = self.services.manager.get_agent(paged_id)
        t_warm = time.monotonic()
        while time.monotonic() - t_warm < 60.0:
            stats = self.services.backend.stats(agent.engine_id) or {}
            if stats.get("model_loaded"):
                break
            await asyncio.sleep(0.5)
        else:
            self.violations.append("page_exhaustion: paged engine never loaded")
            return False
        saw_backpressure = False
        for i in range(6):
            # distinct sessions grow the pool toward organic exhaustion;
            # the armed failpoint covers the deterministic half
            status, msg = await self.chat(paged_id, session=f"pool-{i}")
            if status >= 500:
                self.violations.append(
                    f"page_exhaustion: {msg} got {status} (crash, not backpressure)"
                )
            if status in (202, 429):
                saw_backpressure = True
            await asyncio.sleep(0.1)
        # the engine must still be serving (fresh small session)
        await self.probe_until_ok(paged_id, "page_exhaustion")
        # engine-side accounting: the exhaustions were counted, not hidden
        agent = self.services.manager.get_agent(paged_id)
        stats = self.services.backend.stats(agent.engine_id) or {}
        exhausted = int(stats.get("page_exhausted_total", 0) or 0)
        if stats.get("paged_kv") is not True:
            self.violations.append("page_exhaustion: agent is not serving paged KV")
        if exhausted < 1:
            self.violations.append(
                "page_exhaustion: no exhaustion counted (failpoint not wired?)"
            )
        self.counts["page_exhausted"] = exhausted
        return saw_backpressure and exhausted >= 1

    async def phase_llm_resume(self, llm_id: str) -> bool:
        """Token-identical resume: control session runs turn1+turn2 clean;
        victim session runs turn1, the engine is SIGKILLed, and after the
        watcher respawns it the victim's turn2 (restored from the KV
        snapshot) must match the control's turn2 bit for bit."""

        async def turn(session: str, message: str) -> tuple[int, str]:
            resp = await self.client.post(
                f"/agent/{llm_id}/chat",
                data=json.dumps(
                    {"message": message, "session": session, "max_tokens": 12}
                ),
            )
            doc = await resp.json()
            return resp.status, doc.get("response", "")

        status, _ = await turn("ctl", "alpha alpha alpha")
        assert status == 200, f"llm ctl turn1 got {status}"
        status, ctl_t2 = await turn("ctl", "beta beta")
        assert status == 200, f"llm ctl turn2 got {status}"
        status, _ = await turn("vic", "alpha alpha alpha")
        assert status == 200, f"llm vic turn1 got {status}"
        # The resume guarantee is conditional on a snapshot EXISTING: the
        # engine's limiter defers stagings (durability floor 30 s from the
        # session's first attempt). Wait for the victim's snapshot to land
        # durably — never landing inside the floor is itself a violation.
        kv_key = f"agent:{llm_id}:kvcache:vic"
        t_snap = time.monotonic()
        while self.services.store.get(kv_key) is None:
            if time.monotonic() - t_snap > 45.0:
                self.violations.append(
                    "llm resume: KV snapshot never landed within the "
                    "durability floor"
                )
                return False
            await asyncio.sleep(0.25)

        engine_id = self.services.manager.get_agent(llm_id).engine_id
        self.services.backend.kill_engine_hard(engine_id)
        # recovery probes use a THROWAWAY session: a probe that 502s leaves
        # a pending journal entry that later REPLAYS — pointed at the
        # victim session it would append extra turns and desync the
        # context the token-identical comparison depends on
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            status, _ = await turn("probe-resume", "ping")
            if status == 200:
                recovered = True
                break
            await asyncio.sleep(0.5)
        self.mttr["llm_sigkill"] = round(time.monotonic() - t0, 3) if recovered else -1.0
        if not recovered:
            self.violations.append("llm_sigkill: engine never served again")
            return False
        status, vic_t2 = await turn("vic", "beta beta")
        if status != 200:
            self.violations.append(f"llm resume: vic turn2 got {status}")
            return False
        if vic_t2 != ctl_t2:
            self.violations.append(
                f"token-identical resume violated: {vic_t2!r} != {ctl_t2!r}"
            )
            return False
        return True

    async def phase_park_kill(self, tiered_id: str) -> bool:
        """SIGKILL with a session PARKED in the tiered-KV hierarchy: the
        victim is demoted off-device (host tier + cold store blob) before
        the kill, so the respawned engine has never held its pages — the
        next turn must resume token-identically from the cold tier alone.
        Pins that parking loses nothing a snapshot wouldn't: the cold
        blob is packed from the exact staged arrays BEFORE any int8
        host-tier quantization."""

        async def turn(session: str, message: str) -> tuple[int, str]:
            resp = await self.client.post(
                f"/agent/{tiered_id}/chat",
                data=json.dumps(
                    {"message": message, "session": session, "max_tokens": 12}
                ),
            )
            doc = await resp.json()
            return resp.status, doc.get("response", "")

        status, _ = await turn("pctl", "gamma gamma gamma")
        assert status == 200, f"tiered ctl turn1 got {status}"
        status, ctl_t2 = await turn("pctl", "delta delta")
        assert status == 200, f"tiered ctl turn2 got {status}"
        status, _ = await turn("pvic", "gamma gamma gamma")
        assert status == 200, f"tiered vic turn1 got {status}"
        # explicit park (the proxy's linger policy would get here on its
        # own clock; the soak forces the timing): device pages free, host
        # tier holds the session, and the serve layer writes the exact
        # cold blob durably to the store
        resp = await self.client.post(
            f"/agent/{tiered_id}/park", data=json.dumps({"session": "pvic"})
        )
        doc = await resp.json()
        if resp.status != 200 or not doc.get("parked"):
            self.violations.append(
                f"park_kill: park failed ({resp.status}: {doc})"
            )
            return False
        kv_key = f"agent:{tiered_id}:kvcache:pvic"
        if self.services.store.get(kv_key) is None:
            self.violations.append("park_kill: cold-tier blob missing after park")
            return False
        engine_id = self.services.manager.get_agent(tiered_id).engine_id
        self.services.backend.kill_engine_hard(engine_id)
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            status, _ = await turn("probe-park", "ping")
            if status == 200:
                recovered = True
                break
            await asyncio.sleep(0.5)
        self.mttr["park_kill"] = (
            round(time.monotonic() - t0, 3) if recovered else -1.0
        )
        if not recovered:
            self.violations.append("park_kill: engine never served again")
            return False
        status, vic_t2 = await turn("pvic", "delta delta")
        if status != 200:
            self.violations.append(f"park_kill: vic turn2 got {status}")
            return False
        if vic_t2 != ctl_t2:
            self.violations.append(
                f"park_kill token parity violated: {vic_t2!r} != {ctl_t2!r}"
            )
            return False
        return True

    async def phase_fused_resume(self, fused_id: str) -> bool:
        """SIGKILL mid-FUSED-loop: the same token-identical contract as
        phase_llm_resume, but on a ``fused_decode=true`` engine whose armed
        ``engine.fused_decode`` delay (150 ms per loop dispatch) stretches
        the victim's in-flight turn so the kill lands INSIDE a compiled
        while_loop window. The loop's single packed readback dies with the
        process — nothing of the partial loop was ever on the host — and
        the journaled turn must be rebuilt on the respawned engine from
        the KV snapshot, token-identical to the control's."""

        async def turn(session: str, message: str, n: int = 32):
            resp = await self.client.post(
                f"/agent/{fused_id}/chat",
                data=json.dumps(
                    {
                        "message": message,
                        "session": session,
                        "max_tokens": n,
                        "ignore_eos": True,
                    }
                ),
            )
            doc = await resp.json()
            rid = resp.headers.get("X-Agentainer-Request-ID", "")
            return resp.status, doc.get("response", ""), rid

        engine_id = self.services.manager.get_agent(fused_id).engine_id
        t_warm = time.monotonic()
        while time.monotonic() - t_warm < 90.0:
            stats = self.services.backend.stats(engine_id) or {}
            if stats.get("model_loaded"):
                break
            await asyncio.sleep(0.5)
        else:
            self.violations.append("fused_resume: engine never loaded")
            return False
        if stats.get("fused_decode") is not True:
            self.violations.append("fused_resume: agent is not serving fused decode")
            return False

        status, _, _ = await turn("fuctl", "alpha alpha alpha")
        assert status == 200, f"fused ctl turn1 got {status}"
        status, ctl_t2, _ = await turn("fuctl", "beta beta")
        assert status == 200, f"fused ctl turn2 got {status}"
        status, ctl_t3, _ = await turn("fuctl", "gamma", n=12)
        assert status == 200, f"fused ctl turn3 got {status}"
        status, _, _ = await turn("fuvic", "alpha alpha alpha")
        assert status == 200, f"fused vic turn1 got {status}"
        # resume is conditional on a durable snapshot (same contract as
        # phase_llm_resume — never landing is itself a violation)
        kv_key = f"agent:{fused_id}:kvcache:fuvic"
        t_snap = time.monotonic()
        while self.services.store.get(kv_key) is None:
            if time.monotonic() - t_snap > 45.0:
                self.violations.append("fused_resume: KV snapshot never landed")
                return False
            await asyncio.sleep(0.25)

        # fire turn2 and kill MID-LOOP: the armed fused-dispatch delay
        # makes each while_loop window take >= 150 ms, so 0.25 s into the
        # 32-token turn the process is past prefill and inside (or between)
        # fused loops whose results the host has never seen
        t2_task = asyncio.ensure_future(turn("fuvic", "beta beta"))
        await asyncio.sleep(0.25)
        t_kill = time.monotonic()
        self.services.backend.kill_engine_hard(engine_id)
        status, live_t2, rid = await t2_task
        if status == 200:
            # kill landed after the turn completed — still a valid A/B
            if live_t2 != ctl_t2:
                self.violations.append(
                    f"fused_resume: live turn2 diverged: {live_t2!r} != {ctl_t2!r}"
                )
                return False
        else:
            if not rid:
                self.violations.append(
                    f"fused_resume: turn2 got {status} with no request id"
                )
                return False
            # the acked-by-journal turn replays onto the respawned engine
            # and must settle COMPLETED with the token-identical text
            deadline = time.monotonic() + RECOVERY_CAP_S
            req = None
            while time.monotonic() < deadline:
                req = self.services.journal.get(fused_id, rid)
                if req is not None and req.status == "completed":
                    break
                await asyncio.sleep(0.25)
            if req is None or req.status != "completed":
                self.violations.append(
                    "fused_resume: mid-loop turn never settled "
                    f"({None if req is None else req.status})"
                )
                return False
            import base64 as _b64

            body = _b64.b64decode((req.response or {}).get("body_b64", "") or "")
            try:
                archived = json.loads(body).get("response", "")
            except Exception:
                archived = ""
            if archived != ctl_t2:
                self.violations.append(
                    f"fused_resume: archived turn2 diverged: "
                    f"{archived!r} != {ctl_t2!r}"
                )
                return False
        # recovery probes on a THROWAWAY session (a 502'd probe pointed at
        # fuvic would journal-replay an extra turn and desync the context)
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            s, _, _ = await turn("fuprobe", "ping", n=4)
            if s == 200:
                recovered = True
                break
            await asyncio.sleep(0.5)
        self.mttr["fused_sigkill"] = (
            round(time.monotonic() - t_kill, 3) if recovered else -1.0
        )
        if not recovered:
            self.violations.append("fused_resume: engine never served again")
            return False
        # the next LIVE victim turn continues the spliced session exactly
        status, vic_t3, _ = await turn("fuvic", "gamma", n=12)
        if status != 200:
            self.violations.append(f"fused_resume: vic turn3 got {status}")
            return False
        if vic_t3 != ctl_t3:
            self.violations.append(
                f"fused_resume: post-respawn turn diverged: "
                f"{vic_t3!r} != {ctl_t3!r}"
            )
            return False
        self.counts["fused_loops_after_resume"] = int(
            (
                self.services.backend.stats(
                    self.services.manager.get_agent(fused_id).engine_id
                )
                or {}
            ).get("fused_loops_total", 0)
            or 0
        )
        return True

    async def phase_fused_inject_resume(self, fid: str) -> bool:
        """SIGKILL while a lane is being INJECTED into a running fused loop
        that also holds unverified in-loop speculation state. A long
        repetitive victim turn keeps the device n-gram drafter firing
        (accepted drafts the host has NOT read back yet); 0.15 s in, a
        second session's prefill stages itself into the running loop
        (double-buffered lane injection); 0.15 s later the process is
        SIGKILLed. Everything in flight — the armed staging slot, the
        loop's packed readback, the drafted tokens — dies with the
        process. Both journaled turns must settle COMPLETED on the
        respawned engine token-identical to the controls, and the next
        LIVE victim turn must match the control's bit for bit (extends
        ``fused_resume_token_identical`` to the injection + in-loop-spec
        composition)."""

        async def turn(session: str, message: str, n: int = 32):
            resp = await self.client.post(
                f"/agent/{fid}/chat",
                data=json.dumps(
                    {
                        "message": message,
                        "session": session,
                        "max_tokens": n,
                        "ignore_eos": True,
                    }
                ),
            )
            doc = await resp.json()
            rid = resp.headers.get("X-Agentainer-Request-ID", "")
            return resp.status, doc.get("response", ""), rid

        async def settle_identical(task, want: str, label: str) -> bool:
            status, live, rid = await task
            if status == 200:
                if live != want:
                    self.violations.append(
                        f"fused_inject: live {label} diverged: {live!r} != {want!r}"
                    )
                    return False
                return True
            if not rid:
                self.violations.append(
                    f"fused_inject: {label} got {status} with no request id"
                )
                return False
            deadline = time.monotonic() + RECOVERY_CAP_S
            req = None
            while time.monotonic() < deadline:
                req = self.services.journal.get(fid, rid)
                if req is not None and req.status == "completed":
                    break
                await asyncio.sleep(0.25)
            if req is None or req.status != "completed":
                self.violations.append(
                    f"fused_inject: {label} never settled "
                    f"({None if req is None else req.status})"
                )
                return False
            import base64 as _b64

            body = _b64.b64decode((req.response or {}).get("body_b64", "") or "")
            try:
                archived = json.loads(body).get("response", "")
            except Exception:
                archived = ""
            if archived != want:
                self.violations.append(
                    f"fused_inject: archived {label} diverged: "
                    f"{archived!r} != {want!r}"
                )
                return False
            return True

        engine_id = self.services.manager.get_agent(fid).engine_id
        t_warm = time.monotonic()
        while time.monotonic() - t_warm < 90.0:
            stats = self.services.backend.stats(engine_id) or {}
            if stats.get("model_loaded"):
                break
            await asyncio.sleep(0.5)
        else:
            self.violations.append("fused_inject: engine never loaded")
            return False
        if stats.get("fused_decode") is not True or stats.get("inloop_spec") is not True:
            self.violations.append(
                "fused_inject: agent is not serving fused decode + in-loop spec"
            )
            return False

        # repetitive text keeps the trailing-n-gram drafter matching, so
        # the loop is actually carrying accepted-draft state when killed
        rep = "tick tock tick tock tick tock tick tock"
        status, _, _ = await turn("fictl", rep)
        assert status == 200, f"fused_inject ctl turn1 got {status}"
        status, ctl_t2, _ = await turn("fictl", rep)
        assert status == 200, f"fused_inject ctl turn2 got {status}"
        status, ctl_t3, _ = await turn("fictl", "gamma", n=12)
        assert status == 200, f"fused_inject ctl turn3 got {status}"
        status, ctl_b, _ = await turn("fictl-b", "omega omega omega", n=12)
        assert status == 200, f"fused_inject ctl lane-b got {status}"

        status, _, _ = await turn("fivic", rep)
        assert status == 200, f"fused_inject vic turn1 got {status}"
        kv_key = f"agent:{fid}:kvcache:fivic"
        t_snap = time.monotonic()
        while self.services.store.get(kv_key) is None:
            if time.monotonic() - t_snap > 45.0:
                self.violations.append("fused_inject: KV snapshot never landed")
                return False
            await asyncio.sleep(0.25)

        # fire the long victim turn, let its fused loop get in flight
        # (>= one armed 150 ms dispatch), then fire the second session so
        # its prefill stages into the RUNNING loop, then kill with both
        # the staged lane and the loop's packed readback undelivered
        t2_task = asyncio.ensure_future(turn("fivic", rep))
        await asyncio.sleep(0.15)
        tb_task = asyncio.ensure_future(turn("fivic-b", "omega omega omega", n=12))
        await asyncio.sleep(0.15)
        # sample the DOOMED engine's counters just before the kill: the
        # respawned process starts from zero, so this is the only record
        # of what was actually in flight when the SIGKILL landed
        pre_kill = self.services.backend.stats(engine_id) or {}
        t_kill = time.monotonic()
        self.services.backend.kill_engine_hard(engine_id)
        ok_a = await settle_identical(t2_task, ctl_t2, "vic turn2")
        ok_b = await settle_identical(tb_task, ctl_b, "injected lane")
        if not (ok_a and ok_b):
            return False

        # recovery probes on a THROWAWAY session (same reasoning as
        # phase_fused_resume)
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            s, _, _ = await turn("fiprobe", "ping", n=4)
            if s == 200:
                recovered = True
                break
            await asyncio.sleep(0.5)
        self.mttr["fused_inject_sigkill"] = (
            round(time.monotonic() - t_kill, 3) if recovered else -1.0
        )
        if not recovered:
            self.violations.append("fused_inject: engine never served again")
            return False
        status, vic_t3, _ = await turn("fivic", "gamma", n=12)
        if status != 200:
            self.violations.append(f"fused_inject: vic turn3 got {status}")
            return False
        if vic_t3 != ctl_t3:
            self.violations.append(
                f"fused_inject: post-respawn turn diverged: "
                f"{vic_t3!r} != {ctl_t3!r}"
            )
            return False
        stats = (
            self.services.backend.stats(
                self.services.manager.get_agent(fid).engine_id
            )
            or {}
        )
        # pre-kill: what the dead process had absorbed (injections +
        # staged arms + drafts in flight); post-respawn: the replayed
        # turns' in-loop drafting on the fresh process
        self.counts["fused_inject_injections_pre_kill"] = int(
            pre_kill.get("fused_injections_total", 0) or 0
        ) + int(pre_kill.get("fused_inject_fallbacks_total", 0) or 0)
        self.counts["fused_inject_drafted_pre_kill"] = int(
            pre_kill.get("inloop_spec_drafted", 0) or 0
        )
        self.counts["fused_inject_drafted"] = int(
            stats.get("inloop_spec_drafted", 0) or 0
        )
        return True

    def _affine_replica(self, agent_id: str, session: str) -> str:
        """Which replica the router pinned a session to (the kill target)."""
        router = self.services.router
        with router._lock:
            return router._affinity.get((agent_id, session), "")

    async def phase_replica_failover(self, fleet_id: str) -> bool:
        """Mid-decode failover on a 2-replica LLM fleet. The control
        session runs turn1+turn2 clean. The victim session runs turn1,
        then turn2 is fired and the replica SERVING it is SIGKILLed while
        the decode is in flight. The journaled turn must settle COMPLETED
        on the SURVIVOR (session restored from the store-durable snapshot)
        with a response token-identical to the control's, and the next
        LIVE turn must match the control's turn3 bit for bit."""

        async def turn(session: str, message: str, n: int = 12):
            resp = await self.client.post(
                f"/agent/{fleet_id}/chat",
                data=json.dumps(
                    {
                        "message": message,
                        "session": session,
                        "max_tokens": n,
                        "ignore_eos": True,
                    }
                ),
            )
            doc = await resp.json()
            rid = resp.headers.get("X-Agentainer-Request-ID", "")
            return resp.status, doc.get("response", ""), rid

        # both replicas must be past model load: the phase's very first
        # turn asserts a 200, and a replica still LOADING would 502 it
        agent = self.services.manager.get_agent(fleet_id)
        t_warm = time.monotonic()
        for eid in agent.all_engine_ids():
            while time.monotonic() - t_warm < 90.0:
                stats = self.services.backend.stats(eid) or {}
                if stats.get("model_loaded"):
                    break
                await asyncio.sleep(0.5)
            else:
                self.violations.append(
                    f"replica_failover: replica {eid} never loaded"
                )
                return False

        status, _, _ = await turn("fctl", "alpha alpha alpha")
        assert status == 200, f"fleet ctl turn1 got {status}"
        status, ctl_t2, _ = await turn("fctl", "beta beta", n=32)
        assert status == 200, f"fleet ctl turn2 got {status}"
        status, ctl_t3, _ = await turn("fctl", "gamma", n=12)
        assert status == 200, f"fleet ctl turn3 got {status}"
        status, _, _ = await turn("fvic", "alpha alpha alpha")
        assert status == 200, f"fleet vic turn1 got {status}"
        # the failover resume restores from the durable snapshot: wait for
        # the victim session's snapshot to land (same contract as
        # phase_llm_resume — never landing is itself a violation)
        kv_key = f"agent:{fleet_id}:kvcache:fvic"
        t_snap = time.monotonic()
        while self.services.store.get(kv_key) is None:
            if time.monotonic() - t_snap > 45.0:
                self.violations.append(
                    "replica_failover: KV snapshot never landed"
                )
                return False
            await asyncio.sleep(0.25)

        victim_replica = self._affine_replica(fleet_id, "fvic")
        if not victim_replica:
            self.violations.append("replica_failover: no session affinity recorded")
            return False
        # fire turn2 and kill the serving replica MID-DECODE: the armed
        # decode_step delay makes the 32-token turn take >= 0.6 s, so
        # 0.25 s in the request is past prefill and inside the decode loop
        t2_task = asyncio.ensure_future(turn("fvic", "beta beta", n=32))
        await asyncio.sleep(0.25)
        t_kill = time.monotonic()
        self.services.backend.kill_engine_hard(victim_replica)
        status, live_t2, rid = await t2_task
        # two legitimate outcomes: the dispatch died mid-flight (5xx; the
        # journaled entry replays onto the survivor) or the kill landed
        # before/after the forward and the bounded retry served it live
        if status == 200:
            if live_t2 != ctl_t2:
                self.violations.append(
                    f"replica_failover: live turn2 diverged: {live_t2!r} != {ctl_t2!r}"
                )
                return False
        else:
            if not rid:
                self.violations.append(
                    f"replica_failover: turn2 got {status} with no request id"
                )
                return False
            # the acked-by-journal turn must settle COMPLETED on the
            # survivor with the token-identical continuation
            deadline = time.monotonic() + RECOVERY_CAP_S
            req = None
            while time.monotonic() < deadline:
                req = self.services.journal.get(fleet_id, rid)
                if req is not None and req.status == "completed":
                    break
                await asyncio.sleep(0.25)
            if req is None or req.status != "completed":
                self.violations.append(
                    "replica_failover: mid-decode turn never settled "
                    f"({None if req is None else req.status})"
                )
                return False
            import base64 as _b64

            body = _b64.b64decode((req.response or {}).get("body_b64", "") or "")
            try:
                archived = json.loads(body).get("response", "")
            except Exception:
                archived = ""
            if archived != ctl_t2:
                self.violations.append(
                    f"replica_failover: archived turn2 diverged: "
                    f"{archived!r} != {ctl_t2!r}"
                )
                return False
        # fleet-level MTTR: the agent as a whole keeps serving through the
        # survivor — measured as time-to-next-200 on a throwaway session
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < RECOVERY_CAP_S:
            s, _, _ = await turn("fprobe", "ping", n=4)
            if s == 200:
                recovered = True
                break
            await asyncio.sleep(0.2)
        self.mttr["replica_failover"] = (
            round(time.monotonic() - t_kill, 3) if recovered else -1.0
        )
        if not recovered:
            self.violations.append("replica_failover: fleet never served again")
            return False
        # the next LIVE victim turn continues the spliced session exactly.
        # Routing is deterministic here because EVERY dispatcher (including
        # the replay worker that settled turn2) parses the session hint:
        # fvic's affinity follows the replica that actually executed the
        # failover turn — usually the survivor; the respawned victim only
        # if it came back in time to execute turn2 itself, in which case
        # ITS resident context is equally correct. Either way turn3 lands
        # on the replica holding turn1+turn2, never on a stale restore.
        if not self._affine_replica(fleet_id, "fvic"):
            self.violations.append(
                "replica_failover: failover dispatch recorded no affinity"
            )
            return False
        status, vic_t3, _ = await turn("fvic", "gamma", n=12)
        if status != 200:
            self.violations.append(f"replica_failover: vic turn3 got {status}")
            return False
        if vic_t3 != ctl_t3:
            self.violations.append(
                f"replica_failover: post-failover turn diverged: "
                f"{vic_t3!r} != {ctl_t3!r}"
            )
            return False
        return True

    async def phase_stream_kill(self, fleet_id: str) -> bool:
        """SIGKILL the replica SERVING a live SSE stream mid-decode. The
        tentpole invariant: the client's single connection sees one
        gapless, duplicate-free offset sequence 0..n-1 whose token stream
        is bit-for-bit the undisturbed control's — the proxy fails over to
        the survivor and splices at exactly last_acked_offset + 1, no
        client reconnect involved. The journaled entry settles COMPLETED
        with its stream cursor at the final offset."""

        def parse_frames(raw: bytes):
            frames = []
            for block in raw.split(b"\n\n"):
                if not block.strip() or block.lstrip().startswith(b":"):
                    continue  # keep-alive comments carry no offset
                event, eid, data = "", None, None
                for ln in block.split(b"\n"):
                    if ln.startswith(b"event:"):
                        event = ln[6:].strip().decode()
                    elif ln.startswith(b"id:"):
                        eid = int(ln[3:].strip())
                    elif ln.startswith(b"data:"):
                        data = json.loads(ln[5:].strip())
                frames.append((event, eid, data))
            return frames

        async def turn(session: str, message: str, n: int = 12, stream: bool = False):
            # control and victim MUST send byte-identical prompts (the
            # token comparison is bit-for-bit), so no self.chat sequencing
            resp = await self.client.post(
                f"/agent/{fleet_id}/chat",
                data=json.dumps(
                    {
                        "message": message,
                        "session": session,
                        "stream": stream,
                        "max_tokens": n,
                        "ignore_eos": True,
                    }
                ),
            )
            return resp

        # both replicas past model load (an earlier phase may have killed
        # and respawned one of them)
        agent = self.services.manager.get_agent(fleet_id)
        t_warm = time.monotonic()
        for eid in agent.all_engine_ids():
            while time.monotonic() - t_warm < 90.0:
                stats = self.services.backend.stats(eid) or {}
                if stats.get("model_loaded"):
                    break
                await asyncio.sleep(0.5)
            else:
                self.violations.append(f"stream_kill: replica {eid} never loaded")
                return False

        # undisturbed control: same two turns the victim will run
        resp = await turn("sctl", "epsilon epsilon epsilon")
        await resp.read()
        if resp.status != 200:
            self.violations.append(f"stream_kill: ctl turn1 got {resp.status}")
            return False
        resp = await turn("sctl", "delta delta", n=24, stream=True)
        if resp.status != 200 or not resp.headers.get("Content-Type", "").startswith(
            "text/event-stream"
        ):
            self.violations.append(
                f"stream_kill: ctl stream got {resp.status} "
                f"({resp.headers.get('Content-Type', '')!r})"
            )
            return False
        ctl_frames = parse_frames(await resp.read())
        ctl_tokens = [f[2]["token"] for f in ctl_frames if f[0] == "token"]
        ctl_done = [f[2] for f in ctl_frames if f[0] == "done"]
        if not ctl_tokens or len(ctl_done) != 1:
            self.violations.append("stream_kill: control stream malformed")
            return False

        # victim session: turn1 pins affinity and lands a durable snapshot
        # (the failover resume restores from it, same as replica_failover)
        resp = await turn("svic", "epsilon epsilon epsilon")
        await resp.read()
        if resp.status != 200:
            self.violations.append(f"stream_kill: vic turn1 got {resp.status}")
            return False
        kv_key = f"agent:{fleet_id}:kvcache:svic"
        t_snap = time.monotonic()
        while self.services.store.get(kv_key) is None:
            if time.monotonic() - t_snap > 45.0:
                self.violations.append("stream_kill: KV snapshot never landed")
                return False
            await asyncio.sleep(0.25)
        victim_replica = self._affine_replica(fleet_id, "svic")
        if not victim_replica:
            self.violations.append("stream_kill: no session affinity recorded")
            return False

        # open the victim stream, read a few live events, then SIGKILL the
        # serving replica with the rest of the decode still in flight
        resp = await turn("svic", "delta delta", n=24, stream=True)
        if resp.status != 200:
            self.violations.append(f"stream_kill: vic stream got {resp.status}")
            return False
        rid = resp.headers.get("X-Agentainer-Request-ID", "")
        raw = b""
        seen_tokens = 0
        try:
            while seen_tokens < 3:
                raw += await asyncio.wait_for(
                    resp.content.readuntil(b"\n\n"), timeout=RECOVERY_CAP_S
                )
                seen_tokens = sum(1 for f in parse_frames(raw) if f[0] == "token")
        except (asyncio.TimeoutError, asyncio.IncompleteReadError):
            self.violations.append("stream_kill: stream stalled before the kill")
            return False
        t_kill = time.monotonic()
        self.services.backend.kill_engine_hard(victim_replica)
        try:
            raw += await asyncio.wait_for(resp.content.read(), timeout=RECOVERY_CAP_S)
        except asyncio.TimeoutError:
            self.violations.append("stream_kill: stream never finished after kill")
            self.mttr["stream_kill"] = -1.0
            return False
        frames = parse_frames(raw)
        tokens = [f for f in frames if f[0] == "token"]
        dones = [f[2] for f in frames if f[0] == "done"]
        errors = [f for f in frames if f[0] == "error"]
        ok = True
        # THE invariant: gapless, duplicate-free, bit-for-bit the control
        offsets = [f[1] for f in tokens]
        if offsets != list(range(len(offsets))):
            self.violations.append(f"stream_kill: offsets not gapless: {offsets}")
            ok = False
        if [f[2]["token"] for f in tokens] != ctl_tokens:
            self.violations.append("stream_kill: spliced token stream diverged")
            ok = False
        if len(dones) != 1 or errors:
            self.violations.append(
                f"stream_kill: terminal frames wrong (done={len(dones)}, "
                f"error={len(errors)})"
            )
            ok = False
        elif dones[0].get("response") != ctl_done[0].get("response"):
            self.violations.append("stream_kill: done payload diverged from control")
            ok = False
        self.mttr["stream_kill"] = round(time.monotonic() - t_kill, 3) if ok else -1.0
        # journal: archived COMPLETED with the cursor at the final offset
        if rid:
            req = self.services.journal.get(fleet_id, rid)
            if req is None or req.status != "completed":
                self.violations.append(
                    "stream_kill: streamed entry not archived "
                    f"({None if req is None else req.status})"
                )
                ok = False
            elif req.stream_offset != len(ctl_tokens) - 1:
                self.violations.append(
                    f"stream_kill: cursor {req.stream_offset} != "
                    f"{len(ctl_tokens) - 1}"
                )
                ok = False
        else:
            self.violations.append("stream_kill: no request id on stream")
            ok = False
        return ok

    async def phase_lease_flap(self, fleet_echo_id: str) -> bool:
        """Heartbeat starvation without a death: the replica.lease
        failpoint fails refreshes until its budget is spent, so healthy
        replicas flap SUSPECT (routing excludes them; the pick falls back
        to try-anyway when every replica is excluded). Service must stay
        at 200s throughout, and every replica must return ALIVE."""
        mon = self.services.replica_monitor
        before = mon.suspects_total
        # budget sizing: the monitor refreshes EVERY multi-replica lease
        # each 0.25s tick (4 replicas across both fleets = 16 fires/s), so
        # 24 fires ≈ 1.5s of starvation — past suspect_after_s (1.0) but
        # safely short of dead_after_s (2.0): flapping, not death
        faults.arm(
            "replica.lease", error="ConnectionError", probability=1.0, count=24
        )
        t0 = time.monotonic()
        while time.monotonic() - t0 < 4.0:
            status, msg = await self.chat(fleet_echo_id, session="flap")
            if status != 200:
                self.violations.append(f"lease_flap: {msg} got {status}")
            await asyncio.sleep(0.25)
        faults.disarm("replica.lease")
        if mon.suspects_total <= before:
            self.violations.append(
                "lease_flap: no SUSPECT transition observed (lease seam not wired?)"
            )
            return False
        # refreshes resume: every replica must settle back to ALIVE
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            states = mon.states(fleet_echo_id)
            if states and set(states.values()) == {"alive"}:
                return True
            await asyncio.sleep(0.25)
        self.violations.append(
            f"lease_flap: replicas never returned ALIVE: {mon.states(fleet_echo_id)}"
        )
        return False

    async def phase_route_dead(self, fleet_echo_id: str) -> bool:
        """Stale routing state: one replica is SIGKILLed, the monitor is
        given time to mark it SUSPECT, then router.pick is armed (seeded
        50%) to hand the dead/excluded replica back anyway. Requests in
        the window must be absorbed by the bounded retry-on-next-replica
        (200 via the survivor) or at worst take the durable 502-pending
        path and drain later — never lost. The DEAD transition then fires
        fleet repair, which respawns the victim (the agent has no
        auto_restart watcher, so repair IS the recovery path here)."""
        agent = self.services.manager.get_agent(fleet_echo_id)
        victim = agent.all_engine_ids()[-1]
        router = self.services.router
        stale_before = router.stale_picks_total
        self.services.backend.kill_engine_hard(victim)
        # lease must age past suspect_after_s (1.0) so the victim is
        # actually EXCLUDED — that's what makes a fired pick "stale"
        await asyncio.sleep(1.3)
        faults.arm(
            "router.pick", error="FaultInjected", probability=0.5, seed=SEED, count=12
        )
        ok200 = 0
        for i in range(8):
            status, msg = await self.chat(fleet_echo_id, session=f"rd-{i}")
            if status == 200:
                ok200 += 1
            elif status not in (202, 502):
                self.violations.append(f"route_dead: {msg} got {status}")
            await asyncio.sleep(0.1)
        faults.disarm("router.pick")
        if router.stale_picks_total <= stale_before:
            self.violations.append(
                "route_dead: failpoint never produced a stale pick "
                "(seam not wired?)"
            )
            return False
        if ok200 == 0:
            self.violations.append(
                "route_dead: no request reached the survivor during the window"
            )
            return False
        # repair (DEAD at 2s) respawns the victim: the fleet heals itself
        await self.probe_until_ok(fleet_echo_id, "route_dead")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            states = self.services.replica_monitor.states(fleet_echo_id)
            if states and set(states.values()) == {"alive"}:
                return True
            await asyncio.sleep(0.5)
        self.violations.append(
            "route_dead: victim replica never repaired to ALIVE: "
            f"{self.services.replica_monitor.states(fleet_echo_id)}"
        )
        return False

    # -- invariant settlement ---------------------------------------------
    async def settle(self, agent_ids: list[str]) -> dict:
        inv = {}
        pending_zero = True
        for aid in agent_ids:
            if not await self.drain_pending(aid):
                pending_zero = False
                self.violations.append(
                    f"pending did not converge to 0 for {aid}: "
                    f"{self.services.journal.stats(aid)}"
                )
        inv["pending_converges_to_zero"] = pending_zero

        # every QUEUED ack must have settled COMPLETED (no acked loss)
        lost = []
        for msg, rec in self.acks.items():
            if rec["kind"] == "queued" and rec["rid"]:
                req = self.services.journal.get(rec["agent_id"], rec["rid"])
                if req is None or req.status != "completed":
                    lost.append((msg, None if req is None else req.status))
        if lost:
            self.violations.append(f"acked-but-lost requests: {lost[:5]}")
        inv["no_acked_request_lost"] = not lost

        # history-based exactly-once: NO message may appear twice (double
        # execution). Presence is required only for QUEUED acks — a 202's
        # work executes via replay once engine+store are healthy. A sync
        # 200 during a store blip is DELIVERED but its conversation record
        # is best-effort (the echo engine explicitly chooses availability
        # over convo durability when the store is dark) — counted as
        # degradation, not loss.
        doubles, missing, degraded = [], [], 0
        by_agent: dict[str, list[str]] = {}
        for msg, rec in self.acks.items():
            by_agent.setdefault(rec["agent_id"], []).append(msg)
        for aid, msgs in by_agent.items():
            resp = await self.client.get(f"/agent/{aid}/history")
            if resp.status != 200:
                continue  # llm resume agent history is session-keyed; checked above
            hist = (await resp.json()).get("history", [])
            contents = [t.get("content", "") for t in hist]
            for msg in msgs:
                n = contents.count(msg)
                if n > 1:
                    doubles.append((msg, n))
                elif n == 0 and self.acks[msg]["kind"] == "queued":
                    missing.append(msg)
                elif n == 0 and self.acks[msg]["kind"] == "sync":
                    degraded += 1
        if doubles:
            self.violations.append(f"double execution: {doubles[:5]}")
        if missing:
            self.violations.append(f"queued-acked messages missing from history: {missing[:5]}")
        inv["no_double_execution"] = not doubles
        inv["queued_messages_recorded"] = not missing
        self.counts["history_degraded"] = degraded
        return inv


def torn_aof_check(tmpdir: str) -> dict | None:
    """Native-store AOF torn-tail invariant: truncating mid-record loses
    ONLY the torn record; reopen keeps every complete one AND post-recovery
    appends survive the next reopen (the truncate-before-append fix)."""
    try:
        from agentainer_tpu.native import available

        if not available():
            return None
        from agentainer_tpu.store.native import NativeStore
    except Exception:
        return None
    path = os.path.join(tmpdir, "chaos.aof")
    s = NativeStore(aof_path=path)
    for i in range(8):
        s.set(f"k{i}", f"v{i}")
    s.rpush("torn-list", "x", "y")
    s.close()
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 3)  # tear the last record mid-bytes
    t0 = time.monotonic()
    s2 = NativeStore(aof_path=path)
    recovered = all(s2.get(f"k{i}") == f"v{i}".encode() for i in range(8))
    torn_dropped = s2.lrange("torn-list", 0, -1) == []
    s2.set("after-recovery", "ok")
    s2.close()
    s3 = NativeStore(aof_path=path)
    continue_ok = s3.get("after-recovery") == b"ok" and s3.get("k0") == b"v0"
    s3.close()
    return {
        "recovered_complete_records": recovered,
        "torn_record_dropped": torn_dropped,
        "reopen_and_continue": continue_ok,
        "mttr_s": round(time.monotonic() - t0, 3),
    }


async def run_soak(tmpdir: str) -> dict:
    soak = Soak(tmpdir)
    n_base = 4 if SMOKE else 8
    n_blip = 6 if SMOKE else 12
    n_slow = 3 if SMOKE else 6
    try:
        await soak.start()
        echo_id = await soak.deploy("chaos-echo", "echo")
        llm_id = await soak.deploy(
            "chaos-llm",
            {
                "engine": "llm",
                "config": "tiny",
                "options": {
                    "max_batch": 2,
                    "max_seq": 256,
                    "prefill_chunk": 64,
                    "kv_snapshot_interval_s": 0.5,
                },
            },
        )
        poison_id = await soak.deploy(
            "chaos-poison",
            {
                "engine": "llm",
                "config": "tiny",
                # distinct options → distinct share key → its OWN host
                # process, so the poison env cannot leak into chaos-llm
                "options": {"max_batch": 1, "max_seq": 128, "prefill_chunk": 32},
            },
            env={"ATPU_FAULTS": "engine.prefill:error=RuntimeError,count=2"},
        )
        # 2-replica fleets: the echo fleet exercises lease flapping and
        # stale routing (auto_restart OFF — fleet repair must be the thing
        # that revives a dead replica); the LLM fleet exercises mid-decode
        # failover with token-identical resume on the survivor. Fleet
        # replicas of one agent never share a host process (replica
        # ordinal is in the share key), so killing one leaves the other.
        fleet_echo_id = await soak.deploy(
            "chaos-fleet-echo", "echo", auto_restart=False, replicas=2
        )
        fleet_llm_id = await soak.deploy(
            "chaos-fleet-llm",
            {
                "engine": "llm",
                "config": "tiny",
                # speculative OFF: prompt-lookup drafting can finish a
                # 32-token repetitive turn in <0.15s, turning the phase's
                # "mid-decode" kill into a completed-but-not-yet-durable
                # kill (the PR-5 durability-floor window, asserted by the
                # llm_sigkill phase instead). Plain decode makes the kill
                # land deterministically inside the decode loop, which is
                # the failover case this phase exists to pin.
                "options": {
                    "max_batch": 2,
                    "max_seq": 256,
                    "prefill_chunk": 64,
                    "kv_snapshot_interval_s": 0.5,
                    "speculative": False,
                    # incremental emission on: stream_kill SIGKILLs the
                    # replica serving a live SSE stream mid-decode
                    "streaming": True,
                },
            },
            replicas=2,
            # delay-only decode failpoint in BOTH replicas' engines: the
            # tiny CPU model decodes 32 plain tokens in well under the
            # 0.15s kill offset, so without it the "mid-decode" kill
            # lands after completion (the PR-5 durability-floor window,
            # already pinned by llm_sigkill). 150 ms per decode chunk
            # makes a 32-token turn take >= 0.6 s on every machine —
            # the kill deterministically interrupts the decode loop.
            # Symmetric across replicas and delay-only: greedy token
            # streams are unchanged, so the control comparison holds.
            env={"ATPU_FAULTS": "engine.decode_step:error=none,delay_ms=150"},
        )
        fused_id = await soak.deploy(
            "chaos-fused",
            {
                "engine": "llm",
                "config": "tiny",
                # fused on-device decode loop: up to decode_chunk forwards +
                # in-loop sampling per dispatch, ONE readback at loop exit.
                # speculative OFF for the same reason as chaos-fleet-llm:
                # the kill must land inside plain fused decode, not after a
                # prompt-lookup round already finished the turn.
                "options": {
                    "max_batch": 2,
                    "max_seq": 256,
                    "decode_chunk": 8,
                    "prefill_chunk": 64,
                    "kv_snapshot_interval_s": 0.5,
                    "speculative": False,
                    "fused_decode": True,
                },
            },
            # delay-only failpoint on the FUSED dispatch seam (warmup
            # exempt): 150 ms per while_loop window makes the 32-token
            # victim turn take >= 0.6 s on every machine, so the 0.25 s
            # kill offset deterministically interrupts a window whose
            # packed readback the host has not seen yet. Delay-only: the
            # greedy token stream is unchanged, the control holds.
            env={"ATPU_FAULTS": "engine.fused_decode:error=none,delay_ms=150"},
        )
        fused_inject_id = await soak.deploy(
            "chaos-fused-inject",
            {
                "engine": "llm",
                "config": "tiny",
                # fused loop WITH in-loop speculation (speculative on, so
                # the device n-gram drafter runs inside the loop) and lane
                # injection enabled: the composition whose in-flight state
                # is the largest thing a SIGKILL can vaporize. Distinct
                # options → its own host process.
                "options": {
                    "max_batch": 2,
                    "max_seq": 256,
                    "decode_chunk": 8,
                    "prefill_chunk": 32,
                    "kv_snapshot_interval_s": 0.5,
                    "speculative": True,
                    "fused_decode": True,
                },
            },
            # same delay-only fused-dispatch failpoint as chaos-fused: each
            # while_loop window takes >= 150 ms, so the staggered second
            # session reliably stages into a RUNNING loop and the kill
            # lands with that loop's readback undelivered
            env={"ATPU_FAULTS": "engine.fused_decode:error=none,delay_ms=150"},
        )
        paged_id = await soak.deploy(
            "chaos-paged",
            {
                "engine": "llm",
                "config": "tiny",
                # paged arena with a DELIBERATELY tiny pool (6 pages = 192
                # tokens across all sessions) so organic exhaustion joins
                # the armed engine.page_alloc failpoint below
                "options": {
                    "max_batch": 1,
                    "max_seq": 128,
                    "prefill_chunk": 32,
                    "paged_kv": True,
                    "page_size": 32,
                    "kv_pages": 6,
                },
            },
            env={"ATPU_FAULTS": "engine.page_alloc:error=RuntimeError,count=1"},
        )
        tiered_id = await soak.deploy(
            "chaos-tiered",
            {
                "engine": "llm",
                "config": "tiny",
                # paged arena + tiered-KV hierarchy: sessions park off the
                # device into pinned host RAM (int8) and a cold store
                # blob. park_kill SIGKILLs the engine while a session is
                # parked and asserts its journaled turn resumes
                # token-identically from the cold tier alone.
                "options": {
                    "max_batch": 2,
                    "max_seq": 256,
                    "prefill_chunk": 64,
                    "paged_kv": True,
                    "page_size": 32,
                    "kv_pages": 32,
                    "kv_tiering": True,
                    "kv_snapshot_interval_s": 0.5,
                },
            },
        )

        await soak.phase_baseline(echo_id, n_base)
        await soak.phase_engine_sigkill(echo_id)
        await soak.phase_store_blip(echo_id, n_blip)
        await soak.phase_slow_dispatch(echo_id, n_slow)
        poison_ok = await soak.phase_poisoned_prefill(poison_id)
        backpressured = await soak.phase_page_exhaustion(paged_id)
        token_identical = await soak.phase_llm_resume(llm_id)
        park_identical = await soak.phase_park_kill(tiered_id)
        fused_identical = await soak.phase_fused_resume(fused_id)
        inject_identical = await soak.phase_fused_inject_resume(fused_inject_id)
        lease_ok = await soak.phase_lease_flap(fleet_echo_id)
        route_ok = await soak.phase_route_dead(fleet_echo_id)
        failover_ok = await soak.phase_replica_failover(fleet_llm_id)
        stream_ok = await soak.phase_stream_kill(fleet_llm_id)

        inv = await soak.settle(
            [
                echo_id,
                poison_id,
                paged_id,
                llm_id,
                tiered_id,
                fused_id,
                fused_inject_id,
                fleet_echo_id,
                fleet_llm_id,
            ]
        )
        inv["token_identical_resume"] = token_identical
        inv["park_kill_token_identical"] = park_identical
        inv["fused_resume_token_identical"] = fused_identical
        inv["fused_inject_resume_token_identical"] = inject_identical
        inv["page_exhaustion_backpressure"] = backpressured
        inv["lease_flap_recovers"] = lease_ok
        inv["route_dead_absorbed"] = route_ok
        inv["replica_failover_token_identical"] = failover_ok
        inv["stream_kill_gapless"] = stream_ok
        inv["poisoned_dead_letter"] = poison_ok
    finally:
        await soak.stop()
    aof = torn_aof_check(tmpdir)
    if aof is not None:
        inv["aof_torn_tail_recovery"] = all(
            v for k, v in aof.items() if k != "mttr_s"
        )
        soak.mttr["torn_aof"] = aof["mttr_s"]
    return {
        "invariants": inv,
        "mttr_s": soak.mttr,
        "counts": soak.counts,
        "violations": soak.violations,
        "aof": aof,
    }


def main() -> int:
    t0 = time.monotonic()
    tmpdir = tempfile.mkdtemp(prefix="atpu-chaos-")
    result = asyncio.run(run_soak(tmpdir))
    ok = not result["violations"] and all(result["invariants"].values())
    doc = {
        "metric": "chaos_soak_invariants",
        "value": 1 if ok else 0,
        "unit": "pass",
        "seed": SEED,
        "smoke": SMOKE,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        **result,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    write_artifact("BENCH_chaos.json", doc)
    if not ok:
        print(f"CHAOS SOAK FAILED: {result['violations']}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
