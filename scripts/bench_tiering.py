"""Tiered KV hierarchy benchmark: session capacity, returning-turn TTFT,
and host-tier density.

A/B for the device -> pinned host RAM -> store hierarchy (engine/llm.py):
the SAME tiny paged engine is driven with ``kv_tiering`` off (the
resident-only arena — pool pressure destroys idle context via LRU
reclaim) and on (pool pressure demotes idle sessions to the host tier
with their context intact). Three tiers:

  session capacity     — agent sessions admitted one after another at a
                         FIXED page-pool budget sized well below the
                         offered load. Off: residents cap at the pool and
                         every further admission destroys an idle
                         session's context (it must re-prefill — or 429
                         outright without the destructive reclaim). On:
                         demoted sessions keep their context in host RAM.
                         Headline: context-retaining sessions on/off.
  returning-turn TTFT  — sessions park between turns (the agentic
                         tool-call gap) and return. A/B of turn-2 latency:
                         never-parked control vs parked+prewarmed (the
                         proxy's next-arrival hint promotes concurrently
                         with admission) vs parked-cold (promotion at
                         admission, nothing hidden). The claim: prewarmed
                         p50 within 1.15x of the never-parked control.
  host-tier density    — the SAME parked sessions' host bytes with the
                         int8 per-page-scale cold representation vs exact
                         dtype: how many more parked sessions one host-RAM
                         budget holds.

Host+device-graph behavior is platform-faithful on CPU (absolute numbers
shrink on a real chip; the RATIOS are the claim).

Usage: JAX_PLATFORMS=cpu python scripts/bench_tiering.py
Emits one JSON line on stdout AND writes BENCH_tiering.json at the root.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import (
    make_engine,
    p50 as _p50,
    percentile,
    text_of_tokens,
    write_artifact,
)

MODEL = os.environ.get("ATPU_TIER_MODEL", "tiny")
MAX_SEQ = int(os.environ.get("ATPU_TIER_MAX_SEQ", "256"))
MAX_BATCH = int(os.environ.get("ATPU_TIER_MAX_BATCH", "2"))
PAGE_SIZE = int(os.environ.get("ATPU_TIER_PAGE_SIZE", "32"))
# pool deliberately smaller than the offered session load: 24 pages = 768
# tokens; ~4-page sessions cap residency at ~6 of the 16 offered
KV_PAGES = int(os.environ.get("ATPU_TIER_KV_PAGES", "24"))
CAPACITY_SESSIONS = int(os.environ.get("ATPU_TIER_CAPACITY_SESSIONS", "16"))
SESSION_TOKENS = int(os.environ.get("ATPU_TIER_SESSION_TOKENS", "100"))
TTFT_SESSIONS = int(os.environ.get("ATPU_TIER_TTFT_SESSIONS", "8"))


def _mk_engine(tiering: bool, quantize: int = 1):
    opts = dict(
        max_batch=MAX_BATCH,
        max_seq=MAX_SEQ,
        decode_chunk=8,
        prefill_chunk=128,
        paged_kv=True,
        page_size=PAGE_SIZE,
        kv_pages=KV_PAGES,
    )
    if tiering:
        opts.update(kv_tiering=True, tier_quantize=quantize)
    return make_engine(MODEL, **opts)


async def _capacity(eng, tiering: bool) -> dict:
    """Admit sessions past the pool: how many still HOLD their context
    (device-resident or host-parked) when the dust settles? A session
    whose pages were destructively reclaimed (tiering off) has lost its
    context — its next turn re-prefills from the journal."""
    base = text_of_tokens(eng, SESSION_TOKENS - 24, "tool call result alpha beta. ")
    served = 0
    rejected = 0
    for i in range(CAPACITY_SESSIONS):
        try:
            # UNIQUE leading context per session: shared leading tokens
            # would hit the prefix arena's refcounted pages and every
            # session would fit the pool by aliasing — the tier under
            # test is distinct-context capacity, not prefix sharing
            await eng.chat(f"cap-{i}", f"agent {i:03d} distinct context {i:03d}: {base}", max_tokens=6)
            served += 1
        except Exception:
            rejected += 1  # typed backpressure (pool exhausted, no tiers)
    m = eng.metrics()
    resident = m["resident_sessions"]
    parked = m.get("tier_host_sessions", 0) if tiering else 0
    return {
        "sessions_offered": CAPACITY_SESSIONS,
        "sessions_served": served,
        "sessions_rejected_429": rejected,
        "context_retained": resident + parked,
        "resident": resident,
        "parked_host": parked,
        "pressure_demotions": m.get("tier_pressure_demotions_total", 0),
        "destructive_evictions": eng.session_evictions,
    }


async def _ttft_roundtrip(eng) -> dict:
    """Turn-2 latency for returning sessions, three ways on ONE engine:
    never parked (control), parked then prewarmed (the proxy hint fires
    before the turn arrives — promotion overlaps admission), and parked
    cold (promotion runs inside admission). max_tokens=1 makes the chat
    wall-clock ~TTFT (admission + prefill + first readback)."""
    prompt = text_of_tokens(eng, SESSION_TOKENS - 12, "persona setup gamma delta. ")

    async def turn2(session: str) -> float:
        t0 = time.monotonic()
        await eng.chat(session, "and the next tool call", max_tokens=1)
        return 1000 * (time.monotonic() - t0)

    control, prewarmed, cold = [], [], []
    for i in range(TTFT_SESSIONS):
        s = f"ttft-{i}"
        await eng.chat(s, prompt, max_tokens=6)
        control.append(await turn2(s))  # resident: the never-parked A/B arm
        # parked + prewarmed: the next-arrival hint lands first, so the
        # host->device swap-in runs while this turn is being admitted
        assert await eng.park_session(s) is not None
        assert await eng.prewarm_session(s)
        prewarmed.append(await turn2(s))
        # parked cold: no hint — admission itself promotes, nothing hidden
        assert await eng.park_session(s) is not None
        cold.append(await turn2(s))
    m = eng.metrics()
    return {
        "sessions": TTFT_SESSIONS,
        "control_ms_p50": _p50(control),
        "control_ms_p99": percentile(sorted(control), 0.99),
        "prewarmed_ms_p50": _p50(prewarmed),
        "prewarmed_ms_p99": percentile(sorted(prewarmed), 0.99),
        "cold_ms_p50": _p50(cold),
        "cold_ms_p99": percentile(sorted(cold), 0.99),
        "promote_overlap_ms_p50": m.get("tier_promote_overlap_ms_p50"),
        "prewarm_hits": m.get("tier_prewarm_hits_total", 0),
    }


async def _density(quantize: int) -> dict:
    """Park the same session set and read the host tier's bytes: int8
    per-page scales vs exact dtype."""
    eng = _mk_engine(tiering=True, quantize=quantize)
    try:
        prompt = text_of_tokens(eng, SESSION_TOKENS - 12, "cold context epsilon. ")
        n = 6
        for i in range(n):
            await eng.chat(f"cold-{i}", prompt, max_tokens=6)
            assert await eng.park_session(f"cold-{i}") is not None
        m = eng.metrics()
        return {
            "sessions_parked": m["tier_host_sessions"],
            "host_bytes": m["tier_host_bytes"],
            "quantized_pages": m["tier_quantized_pages"],
        }
    finally:
        eng.shutdown()


async def main() -> dict:
    t0 = time.monotonic()
    eng_off = _mk_engine(tiering=False)
    try:
        capacity_off = await _capacity(eng_off, tiering=False)
    finally:
        eng_off.shutdown()
    eng_on = _mk_engine(tiering=True)
    try:
        capacity_on = await _capacity(eng_on, tiering=True)
    finally:
        eng_on.shutdown()
    eng_ttft = _mk_engine(tiering=True)
    try:
        ttft = await _ttft_roundtrip(eng_ttft)
    finally:
        eng_ttft.shutdown()
    dens_exact = await _density(quantize=0)
    dens_int8 = await _density(quantize=1)

    retained_off = max(1, capacity_off["context_retained"])
    capacity_ratio = round(capacity_on["context_retained"] / retained_off, 2)
    ttft_ratio = (
        round(ttft["prewarmed_ms_p50"] / ttft["control_ms_p50"], 3)
        if ttft["control_ms_p50"]
        else None
    )
    density_ratio = (
        round(dens_exact["host_bytes"] / dens_int8["host_bytes"], 2)
        if dens_int8["host_bytes"]
        else None
    )
    return {
        "metric": "kv_tiering_ab",
        "unit": "ratio",
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "model": MODEL,
        "config": {
            "max_seq": MAX_SEQ,
            "max_batch": MAX_BATCH,
            "page_size": PAGE_SIZE,
            "kv_pages": KV_PAGES,
            "session_tokens": SESSION_TOKENS,
        },
        "capacity": {"off": capacity_off, "on": capacity_on},
        "ttft_roundtrip": ttft,
        "density": {"exact": dens_exact, "int8": dens_int8},
        # headlines: context-retaining session capacity tiering on vs off
        # (claim >= 2x), prewarmed returning-turn p50 vs never-parked
        # control (claim <= 1.15x), exact vs int8 host bytes (claim >= 2x)
        "capacity_ratio": capacity_ratio,
        "prewarmed_ttft_p50_ratio": ttft_ratio,
        "host_density_ratio": density_ratio,
        "wall_s": round(time.monotonic() - t0, 1),
    }


if __name__ == "__main__":
    doc = asyncio.run(main())
    write_artifact("BENCH_tiering.json", doc)
