"""Cross-session prefix KV cache benchmark: cold vs warm-prefix TTFT.

A/B for the prefix arena (engine/llm.py): the SAME engine config is driven
twice, once with ``prefix_cache`` off (every session pays full prefill for
the shared system prompt — the pre-arena engine) and once with it on (the
second session FORKS the cached persona prefix on admission and prefills
only its uncached tail). Measures:

  ttft_ms_p50 (warm/base) — TTFT of probe sessions that share a long
                            system-prompt prefix, after the first session
                            populated the arena (vs the off baseline where
                            every probe re-prefills it)
  prefix_tokens_saved     — prefill tokens the forks skipped; must account
                            for the TTFT difference
  itl_ms_steady           — steady-state decode of a long generation (the
                            regression guard: the arena never touches the
                            decode path)
  flattened per-turn      — gemini-style history-flattened turns: per-turn
                            prompt tokens vs tokens actually prefilled
                            (the stable persona+history head forks; only
                            the window tail re-prefills)

The scheduler/copy artifact being measured is host+device-graph behavior
identical on any JAX platform, so a CPU run is a faithful A/B (absolute
numbers are smaller than on a tunneled TPU, where a skipped 512-token
prefill is worth ~a full chunk wall).

Usage: JAX_PLATFORMS=cpu python scripts/bench_prefix.py
Emits one JSON line on stdout AND writes BENCH_prefix.json at the repo
root (the committed artifact).
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, p50 as _p50, steady_itl, text_of_tokens, write_artifact

MODEL = os.environ.get("ATPU_PFX_MODEL", "tiny")
PROBES = int(os.environ.get("ATPU_PFX_PROBES", "16"))
MAX_SEQ = int(os.environ.get("ATPU_PFX_MAX_SEQ", "2048"))
# shared system-prompt size in TOKENS (the acceptance bar is ≥256; the
# default exercises the full 1024 bucket so the fork skips ~all prefill)
SYS_TOKENS = int(os.environ.get("ATPU_PFX_SYS_TOKENS", "1040"))
FLAT_TURNS = int(os.environ.get("ATPU_PFX_FLAT_TURNS", "6"))


def _mk_engine(prefix_cache: bool):
    return make_engine(
        MODEL,
        max_batch=4,
        max_seq=MAX_SEQ,
        decode_chunk=8,
        prefill_chunk=256,
        prefix_cache=prefix_cache,
    )


_text_of_tokens = text_of_tokens


async def _probe_ttfts(eng, persona: str) -> list[float]:
    """TTFT of PROBES session-less requests sharing the persona prefix,
    each with a distinct user tail (so only the prefix can be reused)."""
    out = []
    for k in range(PROBES):
        r = await eng.generate(
            f"{persona} user question {k} please answer", max_tokens=8, temperature=0.0
        )
        out.append(r["ttft_ms"])
    return out


async def _steady_itl(eng) -> float:
    """Wall-clock ms per generated token of an uncontended long
    generation, best of two passes (regression guard)."""
    return await steady_itl(eng, passes=2, max_tokens=300)


async def _flattened_turns(eng) -> list[dict]:
    """Per-turn prefill cost for gemini-style flattened-history prompting:
    persona + growing history, one fresh generate per turn. With the arena
    on, turn N forks the longest bucket-prefix of turn N-1's prompt."""
    persona = _text_of_tokens(eng, 300, "You are a terse and careful agent. ")
    history: list[str] = []
    turns = []
    for t in range(FLAT_TURNS):
        prompt = persona + "\n\n" + "\n".join(history) + f"\nUser: question {t}\nAssistant:"
        saved0 = eng.prefix_tokens_saved
        r = await eng.generate(prompt, max_tokens=8, temperature=0.0)
        saved = eng.prefix_tokens_saved - saved0
        turns.append(
            {
                "turn": t,
                "prompt_tokens": r["prompt_tokens"],
                "tokens_saved": saved,
                "tokens_prefilled": r["prompt_tokens"] - saved,
                "ttft_ms": r["ttft_ms"],
            }
        )
        history.append(f"User: question {t}")
        history.append(f"Assistant: {r['text']}")
    return turns


async def _measure(prefix_cache: bool) -> dict:
    eng = _mk_engine(prefix_cache)
    try:
        persona = _text_of_tokens(
            eng, SYS_TOKENS, "You are agent seven of the fleet. Be concise and exact. "
        )
        # first session populates the arena (or just prefills, when off)
        cold = await eng.generate(
            persona + " user question cold start", max_tokens=8, temperature=0.0
        )
        ttfts = await _probe_ttfts(eng, persona)
        itl = await _steady_itl(eng)
        flat = await _flattened_turns(eng)
        m = eng.metrics()
        return {
            "prefix_cache": prefix_cache,
            "sys_prompt_tokens": len(eng.tokenizer.encode(persona)),
            "ttft_ms_cold_first_session": round(cold["ttft_ms"], 3),
            "ttft_ms_p50": _p50(ttfts),
            "ttft_samples": [round(x, 2) for x in ttfts],
            "itl_ms_steady": itl,
            "prefix_hits": m["prefix_hits"],
            "prefix_misses": m["prefix_misses"],
            "prefix_tokens_saved": m["prefix_tokens_saved"],
            "prefix_arena_entries": m["prefix_arena_entries"],
            "prefix_arena_bytes": m["prefix_arena_bytes"],
            "prefix_evictions_total": m["prefix_evictions_total"],
            "flattened_turns": flat,
            "flattened_prefilled_total": sum(t["tokens_prefilled"] for t in flat),
            "flattened_prompt_total": sum(t["prompt_tokens"] for t in flat),
            "worker_errors": m["worker_errors"],
        }
    finally:
        eng.shutdown()


async def run() -> dict:
    t0 = time.monotonic()
    base = await _measure(prefix_cache=False)
    warm = await _measure(prefix_cache=True)
    ratio = None
    if base["ttft_ms_p50"]:
        ratio = round(warm["ttft_ms_p50"] / base["ttft_ms_p50"], 3)
    itl_reg = None
    if base["itl_ms_steady"]:
        itl_reg = round(warm["itl_ms_steady"] / base["itl_ms_steady"] - 1.0, 4)
    # tokens_saved accounting: every warm probe should have forked the
    # largest bucket ≤ the persona length
    saved_per_probe = warm["prefix_tokens_saved"] / max(1, PROBES + FLAT_TURNS)
    import jax

    return {
        "metric": "llm_warm_prefix_ttft_p50_over_no_cache",
        "value": ratio,
        "unit": "ratio",
        "platform": jax.default_backend(),
        "model": MODEL,
        "probes": PROBES,
        "no_cache": base,
        "prefix_cache": warm,
        "itl_steady_regression": itl_reg,
        "tokens_saved_per_probe_avg": round(saved_per_probe, 1),
        "wall_s": round(time.monotonic() - t0, 1),
    }


def main() -> None:
    out = asyncio.run(run())
    write_artifact("BENCH_prefix.json", out)
    # acceptance guard (ISSUE 2): warm-prefix TTFT ≤ 0.5× the no-cache
    # baseline, steady ITL regression < 5%, and the forks actually skipped
    # the shared prefix (saved tokens account for the difference)
    ok = (
        out["value"] is not None
        and out["value"] <= 0.5
        and (out["itl_steady_regression"] is None or out["itl_steady_regression"] < 0.05)
        and out["prefix_cache"]["prefix_tokens_saved"] >= 256 * PROBES
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
