"""Mid-decode-arrival admission benchmark: adaptive vs fixed scheduling.

A/B for the admission-aware scheduler (engine/llm.py): the SAME engine
config is driven twice, once with ``adaptive_decode`` off (the round-5
fixed-cadence worker: full decode chunks, hard-blocking readback drains —
a new arrival waits out the in-flight chunk wall before its first prefill
chunk dispatches) and once with it on (chunk ladder + interruptible
drains + multi-tick prefill). Each pass measures:

  admission_ms_p50/p90 — queue-wait phase of probes submitted while two
                         background generations keep the decode loop busy
  itl_ms_p50_steady    — inter-token latency of an UNCONTENDED long
                         generation (the <5% regression guard: adaptive
                         chunking must not tax steady state)

Runs on whatever JAX platform is available — the scheduler artifact being
measured is host-side worker-loop behavior, so a CPU run is a faithful
A/B even though absolute numbers are smaller than on a tunneled TPU. The
default decode_chunk here is 16 (vs the serving default 8): the A/B is
meaningful when the chunk wall dominates the worker loop's few-ms
overhead, which is the TPU regime (8 × 22 ms ITL ≈ 180 ms wall) — on CPU
the tiny model's chunk-8 wall (~8 ms) sits inside loop-overhead noise.

Usage: JAX_PLATFORMS=cpu python scripts/bench_admission.py
Emits one JSON line on stdout; the repo's committed artifact is
BENCH_admission.json.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, percentile as _p

MODEL = os.environ.get("ATPU_ADM_MODEL", "tiny")
PROBES = int(os.environ.get("ATPU_ADM_PROBES", "32"))
DECODE_CHUNK = int(os.environ.get("ATPU_ADM_DECODE_CHUNK", "16"))
MAX_BATCH = int(os.environ.get("ATPU_ADM_MAX_BATCH", "8"))
# burst phase: agentic fan-out — W waves of K simultaneous arrivals while
# decode is busy. Fixed cadence admits ONE first-chunk per full chunk wall
# (probe k waits ~k walls); the adaptive engine admits the wave back to
# back, so the contrast grows with K.
BURST_WAVES = int(os.environ.get("ATPU_ADM_BURST_WAVES", "5"))
BURST_K = int(os.environ.get("ATPU_ADM_BURST_K", "6"))
# multi-chunk probe prompt: keeps pending_prompt non-empty for several
# ticks, so the contention-shrink path is exercised, not just the
# interruptible drain
PROBE_PROMPT = "where does the admission latency go? " * 8


async def _measure(adaptive: bool) -> dict:
    eng = make_engine(
        MODEL,
        max_batch=MAX_BATCH,
        max_seq=512,
        decode_chunk=DECODE_CHUNK,
        prefill_chunk=32,
        adaptive_decode=adaptive,
    )
    try:
        # steady state: long generations with nobody waiting — the ITL
        # guard (adaptive must dispatch full chunks here). Wall-clock per
        # generated token, best of two passes: a p50 over a handful of
        # chunk samples is too noisy for a <5% regression check on a
        # shared host.
        steady: list[float] = []
        for _ in range(3):
            ts = time.monotonic()
            r = await eng.generate("steady state pass", max_tokens=300, temperature=0.0)
            steady.append(
                1000 * (time.monotonic() - ts) / max(1, r["completion_tokens"])
            )
        itl_steady = round(min(steady), 3)
        hist_steady = dict(eng.metrics()["decode_chunk_hist"])

        # mid-decode arrivals: two lanes keep decoding throughout; probes
        # submit while their chunks are in flight
        stop = False

        async def bg(i: int) -> None:
            while not stop:
                # long generations: restart gaps (idle worker → fast
                # admission in BOTH modes) would dilute the contrast
                await eng.generate(
                    f"background load lane {i}", max_tokens=400, temperature=0.0
                )

        tasks = [asyncio.ensure_future(bg(i)) for i in range(2)]
        await asyncio.sleep(0.3)  # decode well under way
        adm: list[float] = []
        ttfts: list[float] = []
        for k in range(PROBES):
            r = await eng.generate(
                f"{PROBE_PROMPT}#{k}", max_tokens=2, temperature=0.0
            )
            bd = r.get("ttft_breakdown") or {}
            if bd.get("queue_ms") is not None:
                adm.append(bd["queue_ms"])
                ttfts.append(r["ttft_ms"])
            await asyncio.sleep(0.01)
        # burst arrivals: K at once, single-chunk prompts (admission is the
        # first-chunk dispatch — short prompts keep the phases clean)
        burst_adm: list[float] = []
        for w in range(BURST_WAVES):
            rs = await asyncio.gather(
                *(
                    eng.generate(
                        f"burst wave {w} member {j}", max_tokens=2, temperature=0.0
                    )
                    for j in range(BURST_K)
                )
            )
            for r in rs:
                bd = r.get("ttft_breakdown") or {}
                if bd.get("queue_ms") is not None:
                    burst_adm.append(bd["queue_ms"])
            await asyncio.sleep(0.05)
        stop = True
        await asyncio.gather(*tasks)
        m = eng.metrics()
        adm.sort()
        ttfts.sort()
        burst_adm.sort()
        return {
            "adaptive_decode": adaptive,
            "decode_chunk": DECODE_CHUNK,
            "probes": len(adm),
            "admission_ms_p50": _p(adm, 0.5),
            "admission_ms_p90": _p(adm, 0.9),
            "ttft_ms_p50": _p(ttfts, 0.5),
            "burst_admission_ms_p50": _p(burst_adm, 0.5),
            "burst_admission_ms_p90": _p(burst_adm, 0.9),
            "burst_size": BURST_K,
            "itl_ms_p50_steady": itl_steady,
            "decode_chunk_hist_steady": hist_steady,
            "decode_chunk_hist": m["decode_chunk_hist"],
            "decode_chunks_shrunk": m["decode_chunks_shrunk"],
            "worker_errors": m["worker_errors"],
        }
    finally:
        eng.shutdown()


async def run() -> dict:
    t0 = time.monotonic()
    fixed = await _measure(adaptive=False)
    adaptive = await _measure(adaptive=True)
    # headline: burst-arrival admission (the agentic fan-out pattern the
    # scheduler change targets); solo-probe admission is recorded alongside
    ratio = None
    if fixed["burst_admission_ms_p50"]:
        ratio = round(
            adaptive["burst_admission_ms_p50"] / fixed["burst_admission_ms_p50"], 3
        )
    solo_ratio = None
    if fixed["admission_ms_p50"]:
        solo_ratio = round(adaptive["admission_ms_p50"] / fixed["admission_ms_p50"], 3)
    itl_reg = None
    if fixed["itl_ms_p50_steady"]:
        itl_reg = round(
            adaptive["itl_ms_p50_steady"] / fixed["itl_ms_p50_steady"] - 1.0, 4
        )
    import jax

    return {
        "metric": "llm_admission_ms_p50_adaptive_over_fixed",
        "value": ratio,
        "unit": "ratio",
        "solo_ratio": solo_ratio,
        "platform": jax.default_backend(),
        "model": MODEL,
        "fixed": fixed,
        "adaptive": adaptive,
        "itl_steady_regression": itl_reg,
        "wall_s": round(time.monotonic() - t0, 1),
    }


def main() -> None:
    out = asyncio.run(run())
    print(json.dumps(out), flush=True)
    # acceptance guard (ISSUE 1): adaptive admission ≤ 0.5× fixed, steady
    # ITL regression < 5% — exit non-zero so a driver sees the miss
    ok = (out["value"] is not None and out["value"] <= 0.5) and (
        out["itl_steady_regression"] is None or out["itl_steady_regression"] < 0.05
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
