"""Streamed-vs-buffered serving benchmark: time-to-first-event under load.

A/B for the SSE token-streaming path (ISSUE 20). The SAME tiny-engine
config is driven through an admission burst (background lanes keep the
decode loop busy while waves of probes arrive), measuring per probe:

  first_event_ms   — submit → the FIRST emit-callback delivery: what an
                     SSE consumer waits before tokens start flowing
                     (engine first-token latency + emission plumbing)
  full_ms          — submit → the complete buffered result: what the
                     stream=false caller waits for the same request

The headline is the p50 ratio full/first — how much sooner a streamed
client sees output under contention. The guard is flag parity: with the
``streaming`` engine option on but no emit callback attached (every
stream=false request), the buffered wall must match a streaming=False
engine within noise — the flag quad's A/B baseline is the flag, and the
emission plumbing must cost nothing when nobody subscribes.

Runs on whatever JAX platform is available: emission is host-side worker
machinery riding the existing per-chunk/fused readbacks, so a CPU run is
a faithful A/B even though absolute latencies are smaller than on a TPU.

Usage: JAX_PLATFORMS=cpu python scripts/bench_streaming.py
Emits one JSON line on stdout; the committed artifact is
BENCH_streaming.json.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _benchlib import make_engine, percentile as _p, write_artifact

MODEL = os.environ.get("ATPU_STREAM_MODEL", "tiny")
WAVES = int(os.environ.get("ATPU_STREAM_WAVES", "4"))
WAVE_K = int(os.environ.get("ATPU_STREAM_WAVE_K", "4"))
PROBE_TOKENS = int(os.environ.get("ATPU_STREAM_PROBE_TOKENS", "192"))
MAX_BATCH = int(os.environ.get("ATPU_STREAM_MAX_BATCH", "8"))
PROBE_PROMPT = "stream the answer back token by token please " * 4
BG_PROMPT = "keep the decode loop busy in the background "


def _mk(streaming: bool):
    return make_engine(
        MODEL,
        max_batch=MAX_BATCH,
        max_seq=512,
        decode_chunk=8,
        prefill_chunk=64,
        streaming=streaming,
    )


async def _burst(eng, with_emit: bool) -> dict:
    """Waves of simultaneous probes against busy background lanes; returns
    per-probe first-event and full-response walls."""
    bg = [
        asyncio.ensure_future(
            eng.generate(BG_PROMPT * (i + 1), max_tokens=700, ignore_eos=True)
        )
        for i in range(2)
    ]
    await asyncio.sleep(0.3)  # background lanes are decoding
    first_ms: list[float] = []
    full_ms: list[float] = []
    ttft_ms: list[float] = []
    try:
        for _ in range(WAVES):

            async def probe():
                t0 = time.monotonic()
                marks: list[float] = []
                emit = (lambda start, ids: marks.append(time.monotonic())) if with_emit else None
                r = await eng.generate(
                    PROBE_PROMPT,
                    max_tokens=PROBE_TOKENS,
                    ignore_eos=True,
                    emit=emit,
                )
                t1 = time.monotonic()
                if marks:
                    first_ms.append(1000 * (marks[0] - t0))
                    if r.get("ttft_ms") is not None:
                        ttft_ms.append(float(r["ttft_ms"]))
                full_ms.append(1000 * (t1 - t0))
                return r

            await asyncio.gather(*[probe() for _ in range(WAVE_K)])
        return {
            "first_ms": sorted(first_ms),
            "full_ms": sorted(full_ms),
            "ttft_ms": sorted(ttft_ms),
        }
    finally:
        for t in bg:
            t.cancel()
        await asyncio.gather(*bg, return_exceptions=True)


async def run() -> dict:
    eng_on = _mk(streaming=True)
    try:
        streamed = await _burst(eng_on, with_emit=True)
        buffered = await _burst(eng_on, with_emit=False)
    finally:
        eng_on.shutdown()
    eng_off = _mk(streaming=False)
    try:
        baseline = await _burst(eng_off, with_emit=False)
    finally:
        eng_off.shutdown()

    first_p50 = _p(streamed["first_ms"], 0.50)
    full_p50 = _p(streamed["full_ms"], 0.50)
    buf_p50 = _p(buffered["full_ms"], 0.50)
    base_p50 = _p(baseline["full_ms"], 0.50)
    return {
        "metric": "stream_first_event_speedup",
        # how much sooner a streamed consumer sees output than a buffered
        # one waits for the full response, same engine, same contention
        "value": round(full_p50 / max(first_p50, 1e-6), 2)
        if first_p50 and full_p50
        else None,
        "unit": "x",
        "model": MODEL,
        "waves": WAVES,
        "wave_k": WAVE_K,
        "probe_tokens": PROBE_TOKENS,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "streamed": {
            "first_event_ms_p50": first_p50,
            "first_event_ms_p90": _p(streamed["first_ms"], 0.90),
            "full_ms_p50": full_p50,
            # the tracking guard: the first emitted event must ride the
            # engine's own first-token latency, not trail the full turn
            "engine_ttft_ms_p50": _p(streamed["ttft_ms"], 0.50),
        },
        "buffered_streaming_engine": {
            "full_ms_p50": buf_p50,
            "full_ms_p90": _p(buffered["full_ms"], 0.90),
        },
        "buffered_baseline_engine": {
            "full_ms_p50": base_p50,
            "full_ms_p90": _p(baseline["full_ms"], 0.90),
        },
        # the stream=false guard: emission plumbing with no subscriber must
        # not tax the buffered path (ratio ~1.0, noise-bounded on CPU)
        "flag_parity_ratio": round(buf_p50 / max(base_p50, 1e-6), 3)
        if buf_p50 and base_p50
        else None,
    }


def main() -> int:
    doc = asyncio.run(run())
    doc["wall_s"] = round(time.monotonic() - T0, 1)
    write_artifact("BENCH_streaming.json", doc)
    return 0


T0 = time.monotonic()

if __name__ == "__main__":
    sys.exit(main())
