"""Benchmark: gpt-agent /chat req/s through the full control plane.

BASELINE.json config #1 — the mock-LLM echo agent behind the real stack:
HTTP proxy + bearer-free agent path + request journal (persistence ON) +
subprocess engine, end to end over real sockets. The reference's only
throughput claim for this path is "thousands of requests/second" with
~1-2 ms proxy overhead (docs/NETWORK_ARCHITECTURE.md:444-448); baseline is
taken as 2000 req/s.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "req/s", "vs_baseline": N}
"""

from __future__ import annotations

import asyncio
import json
import statistics
import sys
import tempfile
import time

BASELINE_REQ_S = 2000.0
N_REQUESTS = 600
CONCURRENCY = 64


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


async def run_bench() -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from agentainer_tpu.config import Config
    from agentainer_tpu.daemon import build_services
    from agentainer_tpu.runtime.local import LocalBackend

    tmp = tempfile.mkdtemp(prefix="atpu-bench-")
    cfg = Config()
    cfg.auth_token = "bench-token"
    backend = LocalBackend(data_dir=tmp, ready_timeout_s=60.0)
    services = build_services(
        config=cfg, backend=backend, console_logs=False, data_dir=tmp
    )
    client = TestClient(TestServer(services.app))
    await client.start_server()
    backend.set_control(f"http://127.0.0.1:{client.server.port}")
    auth = {"Authorization": "Bearer bench-token"}

    resp = await client.post("/agents", json={"name": "bench-echo", "model": "echo"}, headers=auth)
    agent = (await resp.json())["data"]
    resp = await client.post(f"/agents/{agent['id']}/start", headers=auth)
    assert resp.status == 200, await resp.text()
    log(f"agent {agent['id']} running")

    url = f"/agent/{agent['id']}/chat"
    sem = asyncio.Semaphore(CONCURRENCY)
    latencies: list[float] = []

    async def one(i: int) -> None:
        async with sem:
            t0 = time.monotonic()
            async with client.post(url, data=json.dumps({"message": f"bench {i}"})) as r:
                await r.read()
                assert r.status == 200, r.status
            latencies.append(time.monotonic() - t0)

    # warmup
    await asyncio.gather(*(one(i) for i in range(32)))
    latencies.clear()

    t0 = time.monotonic()
    await asyncio.gather(*(one(i) for i in range(N_REQUESTS)))
    wall = time.monotonic() - t0

    stats = services.journal.stats(agent["id"])
    log(f"journal stats: {stats}")
    assert stats["failed"] == 0

    backend.close()
    await client.close()

    reqps = N_REQUESTS / wall
    return {
        "metric": "gpt_agent_chat_req_per_s_e2e_journaled",
        "value": round(reqps, 1),
        "unit": "req/s",
        "vs_baseline": round(reqps / BASELINE_REQ_S, 3),
        "extra": {
            "p50_ms": round(1000 * statistics.median(latencies), 2),
            "p99_ms": round(1000 * sorted(latencies)[int(0.99 * len(latencies))], 2),
            "n": N_REQUESTS,
            "concurrency": CONCURRENCY,
            "journaled": True,
        },
    }


def main() -> None:
    result = asyncio.run(run_bench())
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
